"""PR 7 sketched spectral-stats engine (sq_learn_tpu.sketch): exact
short-circuits, certified-bound validity, the digest-keyed stats cache,
the streamed routes, and the estimator wiring (QKMeans/QPCA/QLSSVC)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from sq_learn_tpu import obs
from sq_learn_tpu.models import QKMeans, QPCA
from sq_learn_tpu.models.qkmeans import MU_GRID
from sq_learn_tpu.ops.linalg import row_norms, smallest_singular_value
from sq_learn_tpu.ops.quantum.norms import _mu_grid, select_mu
from sq_learn_tpu.sketch import cache as stats_cache
from sq_learn_tpu.sketch import engine

GRID = (0.0, 0.25, 0.5, 0.75, 1.0)


@pytest.fixture(autouse=True)
def _fresh_cache():
    stats_cache.clear()
    yield
    stats_cache.clear()


@pytest.fixture
def run():
    rec = obs.enable()
    yield rec
    obs.disable()


def _data(n=2000, m=12, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    # anisotropic + shifted so σ_min / μ are non-degenerate
    X = rng.normal(size=(n, m)) * np.linspace(0.5, 3.0, m) + 0.3
    return X.astype(dtype)


# -- engagement rule / short-circuits ---------------------------------------


class TestEngagement:
    def test_tiny_shapes_disable(self):
        assert engine.resolve_sketch_rows(500, 8, "auto") == 0
        assert engine.resolve_sketch_rows(100, 200, 4096) == 0  # wide
        assert engine.resolve_sketch_rows(70_000, 784, "auto") == 4096

    def test_explicit_and_env_overrides(self, monkeypatch):
        assert engine.resolve_sketch_rows(70_000, 784, 0) == 0
        assert engine.resolve_sketch_rows(70_000, 784, None) == 0
        assert engine.resolve_sketch_rows(70_000, 784, 1024) == 1024
        monkeypatch.setenv("SQ_SKETCH_ROWS", "512")
        assert engine.resolve_sketch_rows(70_000, 784, "auto") == 512
        monkeypatch.setenv("SQ_SKETCH_ROWS", "0")
        assert engine.resolve_sketch_rows(70_000, 784, "auto") == 0

    def test_zero_delta_budget_disables(self, monkeypatch):
        monkeypatch.setenv("SQ_SKETCH_DELTA", "0")
        assert engine.resolve_sketch_rows(70_000, 784, "auto") == 0
        X = _data(400, 6)
        st = engine.spectral_stats(X, GRID)
        assert not st.sketched

    def test_exact_path_matches_exact_kernels(self):
        """Zero-budget/tiny-shape stats are the SAME kernels the fits
        always used — values bit-identical, bounds equal to values."""
        X = _data(400, 6)
        st = engine.spectral_stats(X, GRID)
        assert not st.sketched and st.sample_rows == 0
        Xd = jnp.asarray(X)
        assert st.eta == float(jnp.max(row_norms(Xd, squared=True)))
        assert st.frob == float(jnp.linalg.norm(Xd))
        assert st.sigma_min == float(smallest_singular_value(Xd))
        np.testing.assert_array_equal(
            st.mu_vals, np.asarray(_mu_grid(Xd, GRID), np.float64))
        np.testing.assert_array_equal(st.mu_vals, st.mu_upper)
        assert st.sigma_min_lower == st.sigma_min
        assert st.conservative_mu() == select_mu(GRID, st.mu_vals, st.frob)


# -- certified bounds --------------------------------------------------------


class TestBounds:
    def _check(self, X, seed):
        Xd = jnp.asarray(X)
        st = engine.spectral_stats(
            X, GRID, sketch=256, rng=np.random.default_rng(seed),
            audit=False)
        assert st.sketched and st.sample_rows == 256
        # η / ‖A‖_F are exact by construction (one full cheap pass)
        assert st.eta == pytest.approx(
            float(jnp.max(row_norms(Xd, squared=True))), rel=1e-5)
        assert st.frob == pytest.approx(float(jnp.linalg.norm(Xd)),
                                        rel=1e-5)
        # σ lower bound: never above the true σ_min (float-noise slack)
        sigma_true = float(smallest_singular_value(Xd))
        assert st.sigma_min_lower <= sigma_true * (1 + 1e-5)
        # μ upper bounds: per grid point, never below the true μ_p
        mu_true = np.asarray(_mu_grid(Xd, GRID), np.float64)
        assert np.all(st.mu_upper >= mu_true * (1 - 1e-5))
        # the conservative winner never exceeds the exact Frobenius norm
        assert st.conservative_mu()[1] <= st.frob * (1 + 1e-12)

    def test_bounds_hold_single_seed(self):
        self._check(_data(2000, 12), seed=7)

    @pytest.mark.slow
    def test_bounds_hold_across_seeds(self):
        """Statistical tier: the (ε_stat, δ_stat) claims across many
        sample draws and data distributions. With δ_stat = 0.05 a single
        violated seed among 20×2 draws is already unlikely but possible;
        the engine's bounds are distribution-free finite-sample results,
        so zero violations is the expected outcome."""
        violations = 0
        for seed in range(20):
            X = _data(2000, 12, seed=seed % 5)
            Xd = jnp.asarray(X)
            st = engine.spectral_stats(
                X, GRID, sketch=256, rng=np.random.default_rng(100 + seed),
                audit=False)
            sigma_true = float(smallest_singular_value(Xd))
            mu_true = np.asarray(_mu_grid(Xd, GRID), np.float64)
            if st.sigma_min_lower > sigma_true * (1 + 1e-5):
                violations += 1
            if np.any(st.mu_upper < mu_true * (1 - 1e-5)):
                violations += 1
        assert violations == 0

    def test_vacuous_sigma_bound_falls_back_to_plugin(self):
        st = engine.spectral_stats(_data(2000, 12), GRID, sketch=256,
                                   audit=False)
        if st.sigma_min_lower == 0.0:
            assert not st.certified_sigma()
            assert st.condition_number() == 1.0 / st.sigma_min
        else:
            assert st.certified_sigma()
            assert st.condition_number() == 1.0 / st.sigma_min_lower

    def test_info_is_jsonable(self):
        import json

        st = engine.spectral_stats(_data(2000, 12), GRID, sketch=256,
                                   audit=False)
        json.dumps(st.info())


# -- digest-keyed stats cache ------------------------------------------------


class TestStatsCache:
    def test_hit_and_miss_counters(self, run):
        key = stats_cache.key_for(_data(), "t", 1)
        assert stats_cache.lookup(key) is None
        stats_cache.store(key, "payload")
        assert stats_cache.lookup(key) == "payload"
        counters = run.counters
        assert counters["stats_cache.misses"] == 1
        assert counters["stats_cache.hits"] == 1

    def test_mutation_invalidates(self):
        X = _data()
        k1 = stats_cache.key_for(X, "t")
        X[0, 0] += 1.0  # first row is always in the strided digest
        k2 = stats_cache.key_for(X, "t")
        assert k1 != k2
        X[-1, -1] += 1.0  # so is the last
        assert stats_cache.key_for(X, "t") != k2

    def test_config_is_part_of_the_key(self):
        X = _data()
        assert (stats_cache.key_for(X, "t", 256, 0.05)
                != stats_cache.key_for(X, "t", 512, 0.05))
        assert (stats_cache.key_for(X, "t", 256, 0.05)
                != stats_cache.key_for(X, "u", 256, 0.05))

    def test_disabled_by_env(self, monkeypatch):
        monkeypatch.setenv("SQ_STATS_CACHE", "0")
        assert stats_cache.key_for(_data(), "t") is None
        stats_cache.store(None, "x")
        assert stats_cache.lookup(None) is None

    def test_lru_bound(self):
        for i in range(stats_cache.MAX_ENTRIES + 3):
            stats_cache.store(("k", i), i)
        assert stats_cache.lookup(("k", 0)) is None
        assert stats_cache.lookup(
            ("k", stats_cache.MAX_ENTRIES + 2)) is not None


# -- estimator wiring: QKMeans -----------------------------------------------


class TestQKMeansWiring:
    def test_small_fit_stays_exact_and_matches_sketch_off(self):
        X = _data(600, 8)
        a = QKMeans(n_clusters=3, delta=0.5, true_distance_estimate=False,
                    random_state=0, max_iter=10, sketch="auto").fit(X)
        stats_cache.clear()
        b = QKMeans(n_clusters=3, delta=0.5, true_distance_estimate=False,
                    random_state=0, max_iter=10, sketch=0).fit(X)
        assert not a.sketch_info_["sketched"]
        assert a.eta_ == b.eta_
        assert a.mu_ == b.mu_
        assert a.condition_number_ == b.condition_number_
        np.testing.assert_array_equal(a.labels_, b.labels_)

    def test_host_route_sketched_is_conservative(self, monkeypatch):
        monkeypatch.setenv("SQ_SKETCH_ROWS", "256")
        X = _data(2000, 12)
        sk = QKMeans(n_clusters=3, delta=0.5, true_distance_estimate=False,
                     random_state=0, max_iter=10).fit(X)
        stats_cache.clear()
        monkeypatch.setenv("SQ_SKETCH_ROWS", "0")
        ex = QKMeans(n_clusters=3, delta=0.5, true_distance_estimate=False,
                     random_state=0, max_iter=10).fit(X)
        assert sk.ingest_ == "host" and ex.ingest_ == "host"
        assert sk.sketch_info_["sketched"]
        assert not ex.sketch_info_["sketched"]
        # clustering identical — the sketch only feeds the cost model
        np.testing.assert_array_equal(sk.labels_, ex.labels_)
        # conservative folding: μ never below the exact winner, and the
        # runtime model inputs stay finite
        assert sk.mu_ >= ex.mu_ * (1 - 1e-6)
        assert np.isfinite(sk.condition_number_)

    def test_sweep_computes_stats_once_per_dataset(self, run, monkeypatch):
        """The frontier-sweep contract (acceptance criterion): refits over
        the SAME data at different (ε, δ) recompute spectral stats at most
        once — every later fit is a digest-cache hit."""
        monkeypatch.setenv("SQ_SKETCH_ROWS", "256")
        X = _data(2000, 12)
        for i, delta in enumerate((0.5, 0.7, 0.3, 0.9)):
            QKMeans(n_clusters=3, delta=delta, max_iter=5,
                    true_distance_estimate=False, random_state=i).fit(X)
        counters = run.counters
        assert counters["stats_cache.misses"] == 1
        assert counters["stats_cache.hits"] == 3
        assert counters["sketch.estimates"] == 1
        snap = obs.snapshot()
        assert snap["stats_cache_hits"] == 3
        assert snap["sketch_estimates"] == 1

    def test_mutated_input_recomputes(self, run, monkeypatch):
        monkeypatch.setenv("SQ_SKETCH_ROWS", "256")
        X = _data(2000, 12)
        QKMeans(n_clusters=3, delta=0.5, max_iter=5, random_state=0,
                true_distance_estimate=False).fit(X)
        X[0] += 1.0
        QKMeans(n_clusters=3, delta=0.5, max_iter=5, random_state=0,
                true_distance_estimate=False).fit(X)
        assert run.counters["stats_cache.misses"] == 2
        assert run.counters.get("stats_cache.hits", 0) == 0

    def test_fused_path_sketched(self, monkeypatch):
        """The accelerator fused fit consumes the sketched prestats
        components and folds bounds at the single fetch."""
        monkeypatch.setenv("SQ_SKETCH_ROWS", "256")
        X = _data(2000, 12)
        est = QKMeans(n_clusters=3, delta=0.5, max_iter=5, random_state=0,
                      true_distance_estimate=False)
        w = np.ones(X.shape[0], np.float32)
        out = est._fit_fused(X, w, 0.5, "delta")
        assert out is est
        assert est.sketch_info_["sketched"]
        assert est.sketch_info_["sample_rows"] == 256
        assert np.isfinite(est.mu_) and np.isfinite(est.condition_number_)

    def test_fused_path_serves_cache_hit(self, run, monkeypatch):
        monkeypatch.setenv("SQ_SKETCH_ROWS", "256")
        X = _data(2000, 12)
        w = np.ones(X.shape[0], np.float32)
        a = QKMeans(n_clusters=3, delta=0.5, max_iter=5, random_state=0,
                    true_distance_estimate=False)
        assert a._fit_fused(X, w, 0.5, "delta") is a
        b = QKMeans(n_clusters=3, delta=0.7, max_iter=5, random_state=1,
                    true_distance_estimate=False)
        assert b._fit_fused(X, w, 0.7, "delta") is b
        assert run.counters["stats_cache.hits"] == 1
        assert b.sketch_info_ == a.sketch_info_

    def test_streamed_route_sketched(self, monkeypatch):
        monkeypatch.setenv("SQ_SKETCH_ROWS", "256")
        monkeypatch.setenv("SQ_STREAM_TILE_BYTES", str(64 * 1024))
        X = _data(4000, 12)
        est = QKMeans(n_clusters=3, delta=0.5, max_iter=5, random_state=0,
                      use_pallas=False, true_distance_estimate=False).fit(X)
        assert est.ingest_ == "streamed"
        assert est.sketch_info_["sketched"]
        assert np.isfinite(est.mu_)


# -- estimator wiring: QPCA / QLSSVC ----------------------------------------


class TestQPCAWiring:
    def test_tiny_mu_parity_with_best_mu(self):
        from sq_learn_tpu.ops.quantum import best_mu

        X = _data(300, 10, dtype=np.float64)
        p = QPCA(n_components=4, svd_solver="full", random_state=0,
                 compute_mu=True).fit(X)
        Xc = jnp.asarray(X) - jnp.mean(jnp.asarray(X), axis=0)
        desc, val = best_mu(Xc, 0.0, step=0.1)
        assert (p.norm_muA, p.muA) == (desc, val)
        assert not p.sketch_info_["sketched"]

    def test_sketched_mu_is_upper_bound_and_cached(self, run, monkeypatch):
        from sq_learn_tpu.ops.quantum import best_mu

        monkeypatch.setenv("SQ_SKETCH_ROWS", "256")
        X = _data(2000, 12, dtype=np.float64)
        p = QPCA(n_components=4, svd_solver="full", random_state=0,
                 compute_mu=True).fit(X)
        assert p.sketch_info_["sketched"]
        Xc = jnp.asarray(X) - jnp.mean(jnp.asarray(X), axis=0)
        _, exact = best_mu(Xc, 0.0, step=0.1)
        assert p.muA >= exact * (1 - 1e-6)
        p2 = QPCA(n_components=4, svd_solver="full", random_state=0,
                  compute_mu=True).fit(X)
        assert p2.muA == p.muA
        assert run.counters["stats_cache.hits"] == 1

    def test_no_mu_fit_clears_sketch_info(self):
        X = _data(300, 10, dtype=np.float64)
        p = QPCA(n_components=4, svd_solver="full", random_state=0,
                 compute_mu=True).fit(X)
        assert p.sketch_info_ is not None
        p.compute_mu = False
        p.fit(X)
        assert p.sketch_info_ is None


class TestQLSSVCWiring:
    def test_alpha_f_parity_and_cache(self, run):
        from sq_learn_tpu.models import QLSSVC

        rng = np.random.default_rng(3)
        X = rng.normal(size=(60, 5))
        y = np.where(rng.normal(size=60) > 0, 1.0, -1.0)
        clf = QLSSVC().fit(X, y)
        ref = float(np.sqrt(60) + 1.0 / clf.penalty
                    + np.linalg.norm(X, ord="fro") ** 2)
        assert clf.alpha_F_ == pytest.approx(ref, rel=1e-12)
        QLSSVC(penalty=0.5).fit(X, y)  # same data: ‖X‖_F² served cached
        assert run.counters["stats_cache.hits"] == 1


# -- streaming routes --------------------------------------------------------


class TestStreamingRoutes:
    def test_streamed_spectral_stats_matches_host(self, monkeypatch):
        from sq_learn_tpu.streaming import streamed_spectral_stats

        monkeypatch.setenv("SQ_STREAM_TILE_BYTES", str(32 * 1024))
        X = _data(2000, 12)
        st_s = streamed_spectral_stats(X, GRID, sketch=256,
                                       rng=np.random.default_rng(9))
        st_h = engine.spectral_stats(X, GRID, sketch=256,
                                     rng=np.random.default_rng(9),
                                     audit=False)
        assert st_s.sketched and st_h.sketched
        # identical sample (same rng), cheap pass differs only in
        # accumulation dtype (device f32 tiles vs host f64 einsum)
        assert st_s.eta == pytest.approx(st_h.eta, rel=1e-4)
        assert st_s.frob == pytest.approx(st_h.frob, rel=1e-4)
        assert st_s.sigma_min == pytest.approx(st_h.sigma_min, rel=1e-4)
        np.testing.assert_allclose(st_s.mu_upper, st_h.mu_upper, rtol=1e-3)

    def test_streamed_spectral_stats_zero_budget_exact(self):
        from sq_learn_tpu.streaming import streamed_spectral_stats

        X = _data(500, 8)
        st = streamed_spectral_stats(X, GRID)  # tiny: short-circuit
        assert not st.sketched
        assert st.sigma_min == float(
            smallest_singular_value(jnp.asarray(X)))

    def test_streamed_resident_put_round_trip(self, run, monkeypatch):
        from sq_learn_tpu.streaming import streamed_resident_put

        X = _data(300, 7)
        out = streamed_resident_put(X, max_bytes=4096)
        np.testing.assert_array_equal(np.asarray(out), X)
        assert "streaming.assemble" in obs.watchdog.report()

    def test_put_host_delegates_to_streaming(self, run):
        from sq_learn_tpu._config import _put_host

        X = _data(300, 7)
        out = _put_host(X, None, max_bytes=4096)
        np.testing.assert_array_equal(np.asarray(out), X)
        # as_device_array's placement helper rides the supervised
        # streaming path above the byte cap (the removed
        # chunked_device_put wrapper is pinned in test_config_device)
        assert "streaming.assemble" in obs.watchdog.report()
        assert run.counters["streaming.tiles"] >= 2


# -- observability: auditor, guarantee sites, report section ----------------


class TestSketchObservability:
    def test_sketched_run_audits_clean(self, run, monkeypatch):
        monkeypatch.setenv("SQ_SKETCH_ROWS", "256")
        X = _data(2000, 12)
        QKMeans(n_clusters=3, delta=0.5, max_iter=5, random_state=0,
                true_distance_estimate=False).fit(X)
        summary = obs.guarantees.audit()
        assert "sketch.mu" in summary
        assert summary["sketch.mu"]["violations"] == 0
        assert not any(a["flagged"] for a in summary.values())

    def test_exact_route_records_short_circuit(self, run):
        X = _data(600, 8)
        QKMeans(n_clusters=3, delta=0.5, max_iter=5, random_state=0,
                true_distance_estimate=False).fit(X)
        sc = [g for g in run.guarantee_records
              if g.get("site") == "sketch.stats"]
        assert sc and all(g.get("short_circuit") for g in sc)
        assert not any(g.get("violated") for g in sc)

    def test_report_section_and_schema(self, monkeypatch, tmp_path):
        from sq_learn_tpu.obs import report
        from sq_learn_tpu.obs.schema import validate_jsonl
        from sq_learn_tpu.obs.trace import load_jsonl

        path = str(tmp_path / "run.jsonl")
        monkeypatch.setenv("SQ_SKETCH_ROWS", "256")
        obs.enable(path)
        try:
            X = _data(2000, 12)
            for d in (0.5, 0.7):
                QKMeans(n_clusters=3, delta=d, max_iter=5, random_state=0,
                        true_distance_estimate=False).fit(X)
        finally:
            obs.disable()
        result = validate_jsonl(path)
        assert result["errors"] == []
        summary = report.summarize(load_jsonl(path))
        assert summary["sketch"]["cache_hits"] == 1
        assert summary["sketch"]["estimates"] == 1
        text = report.render(summary)
        assert "spectral-stats cache / sketch savings" in text
        assert "1 hits / 1 misses" in text

    def test_audit_cap_skips_large_matrices(self, run, monkeypatch):
        monkeypatch.setenv("SQ_SKETCH_AUDIT_ELEMS", "100")
        st = engine.spectral_stats(_data(2000, 12), GRID, sketch=256)
        assert st.sketched
        assert not [g for g in run.guarantee_records
                    if g.get("site") == "sketch.mu"]
