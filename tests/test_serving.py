"""Serving-layer contract tests (ISSUE 9).

The load-bearing ones: per-request responses are bit-equal between a
fault-injected run (supervised-put retries, breaker trip, host-route
degrade) and a clean run with ZERO requests lost; the retracing
watchdog's ≤1-compile-per-(bucket, dtype, model-shape) budget holds
under mixed request sizes with ``SQ_OBS_STRICT=1`` armed; and the
registry refuses digest-mismatched checkpoints instead of serving them.
All deterministic legs run the dispatcher in ``background=False`` mode
(submission-order batching, no timers), so the parity claims are exact,
not probabilistic.
"""

import numpy as np
import pytest

from sq_learn_tpu import obs
from sq_learn_tpu.models import QKMeans, TruncatedSVD
from sq_learn_tpu.obs.schema import validate_record
from sq_learn_tpu.resilience import faults
from sq_learn_tpu.resilience.supervisor import breaker
from sq_learn_tpu.serving import (MicroBatchDispatcher, ModelRegistry,
                                  ServingModel, SloTracker, SloViolation)
from sq_learn_tpu.serving import cache as serve_cache
from sq_learn_tpu.serving.slo import percentile
from sq_learn_tpu.utils.checkpoint import save_estimator


@pytest.fixture(scope="module")
def fitted():
    rng = np.random.default_rng(0)
    m = 12
    X = (rng.normal(size=(400, m))
         + 5.0 * rng.integers(0, 3, size=(400, 1))).astype(np.float32)
    qkm = QKMeans(n_clusters=3, random_state=0, n_init=1).fit(X)
    svd = TruncatedSVD(n_components=3, random_state=0).fit(X)
    return {"X": X, "m": m, "qkm": qkm, "svd": svd}


@pytest.fixture
def registry(fitted):
    reg = ModelRegistry()
    reg.register("a", fitted["qkm"])
    reg.register("b", fitted["svd"])
    return reg


@pytest.fixture(autouse=True)
def _serving_hygiene():
    serve_cache.clear()
    yield
    serve_cache.clear()
    faults.disarm()
    breaker.reset("test teardown")
    if obs.enabled():
        obs.disable()


def _requests(fitted, n=24, sizes=(1, 5, 17, 40)):
    rng = np.random.default_rng(7)
    return [rng.normal(size=(sizes[i % len(sizes)], fitted["m"]))
            .astype(np.float32) for i in range(n)]


# -- batching / parity -------------------------------------------------------


def test_microbatch_parity_and_ordering(registry, fitted):
    reqs = _requests(fitted)
    d = MicroBatchDispatcher(registry, background=False, max_batch_rows=64)
    futs = [d.submit("a", "predict", r) for r in reqs]
    d.flush()
    qkm = fitted["qkm"]
    for r, f in zip(reqs, futs):
        out = f.result(timeout=10)
        assert out.shape == (r.shape[0],)
        assert np.array_equal(out, qkm.predict(r))
    slo = d.close()
    assert slo["requests"] == len(reqs)
    # coalescing really happened: far fewer dispatches than requests
    assert slo["batches"] < len(reqs)


def test_transform_ops_and_projection(registry, fitted):
    d = MicroBatchDispatcher(registry, background=False)
    r = _requests(fitted, n=1)[0]
    dist = d.serve("a", "transform", r)
    np.testing.assert_allclose(dist, fitted["qkm"].transform(r), atol=1e-4)
    proj = d.serve("b", "transform", r)
    np.testing.assert_allclose(proj, fitted["svd"].transform(r), atol=1e-4)
    d.close()


def test_single_row_and_validation_errors(registry, fitted):
    d = MicroBatchDispatcher(registry, background=False)
    row = np.zeros(fitted["m"], np.float32)  # 1D: one sample
    assert d.serve("a", "predict", row).shape == (1,)
    with pytest.raises(KeyError):
        d.submit("nope", "predict", row)
    with pytest.raises(KeyError):
        d.submit("b", "predict", row)  # SVD surface serves no predict
    with pytest.raises(ValueError):
        d.submit("a", "predict", np.zeros((2, fitted["m"] + 1), np.float32))
    with pytest.raises(ValueError):
        d.submit("a", "predict", np.zeros((1, 2, 3), np.float32))
    d.close()
    with pytest.raises(RuntimeError):
        d.submit("a", "predict", row)  # closed dispatcher refuses


def test_background_worker_serves_concurrent_clients(registry, fitted):
    import threading

    reqs = _requests(fitted, n=40)
    qkm = fitted["qkm"]
    with MicroBatchDispatcher(registry, max_wait_ms=1.0) as d:
        outs = [None] * 4

        def client(i):
            outs[i] = [(r, d.submit("a", "predict", r).result(timeout=30))
                       for r in reqs[i::4]]

        ts = [threading.Thread(target=client, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    for chunk in outs:
        for r, o in chunk:
            assert np.array_equal(o, qkm.predict(r))


def test_submit_many_matches_submit(registry, fitted):
    reqs = _requests(fitted, n=8)
    d = MicroBatchDispatcher(registry, background=False)
    futs = d.submit_many([("a", "predict", r) for r in reqs])
    d.flush()
    many = [f.result(timeout=10) for f in futs]
    d.close()
    d2 = MicroBatchDispatcher(registry, background=False)
    one = [d2.serve("a", "predict", r) for r in reqs]
    d2.close()
    assert all(np.array_equal(x, y) for x, y in zip(many, one))


# -- watchdog / compile budget ----------------------------------------------


def test_compile_budget_under_mixed_sizes_strict(registry, fitted,
                                                 monkeypatch):
    """Mixed request sizes must stay within ≤1 compile per (bucket,
    dtype, model-shape) — enforced by the watchdog, with strict mode
    armed so an excess compile would RAISE, failing this test."""
    monkeypatch.setenv("SQ_OBS_STRICT", "1")
    obs.enable()
    d = MicroBatchDispatcher(registry, background=False, max_batch_rows=64)
    for r in _requests(fitted, n=30, sizes=(1, 2, 3, 5, 9, 17, 33, 40)):
        d.submit("a", "predict", r)
    d.flush()
    d.close()
    report = obs.watchdog.report()
    site = report["serving.predict_centers"]
    assert not site["over_budget"]
    assert site["compiles"] <= site["budget"]
    obs.disable()


# -- degradation under failure ----------------------------------------------


def test_degrade_path_zero_lost_bit_equal(registry, fitted, monkeypatch):
    """The ISSUE 9 acceptance scenario: under an SQ_FAULTS schedule that
    exhausts the supervised put's retries AND trips the breaker, every
    request is still answered, responses are bit-equal to the unfaulted
    run, ordering is preserved, and the watchdog budget holds under
    SQ_OBS_STRICT=1."""
    monkeypatch.setenv("SQ_RETRY_BACKOFF_S", "0.001")
    monkeypatch.setenv("SQ_BREAKER_K", "3")
    monkeypatch.setenv("SQ_OBS_STRICT", "1")
    reqs = _requests(fitted, n=24)

    def run():
        serve_cache.clear()
        obs.enable()
        d = MicroBatchDispatcher(registry, background=False,
                                 max_batch_rows=64)
        futs = [d.submit("a", "predict", r) for r in reqs]
        d.flush()
        outs = [f.result(timeout=30) for f in futs]
        slo = d.close()
        rec = obs.disable()
        return outs, slo, rec

    clean, slo_clean, _ = run()
    assert slo_clean["degraded"] == 0

    # batch 1 fails every put attempt: retries exhaust (terminal put
    # failure -> degrade) and the 3rd consecutive failure trips the
    # breaker, so later batches preflight straight to the host route
    faults.arm("put_fail:tiles=1,times=10")
    faulted, slo_faulted, rec = run()
    faults.disarm()
    breaker.reset("test: degrade leg done")

    assert len(faulted) == len(reqs)  # zero requests lost
    assert all(np.array_equal(a, b) for a, b in zip(clean, faulted))
    assert slo_faulted["degraded"] >= 1
    assert slo_faulted["requests"] == len(reqs)
    trip = [e for e in rec.breaker_events if e.get("state") == "open"]
    assert trip, "breaker never tripped under the fault schedule"


def test_open_breaker_routes_host_without_supervised_put(registry, fitted,
                                                         monkeypatch):
    """With the breaker already OPEN, dispatch must not touch the
    supervised put at all (a wedged relay would stall it) — straight to
    the host route, still answering every request."""
    monkeypatch.setenv("SQ_BREAKER_COOLDOWN_S", "3600")
    breaker.reset("test setup")
    for _ in range(3):
        breaker.record_failure("test wedge")
    assert breaker.state() == "open"
    calls = {"puts": 0}
    from sq_learn_tpu.resilience import supervisor as sup

    real_put = sup.put

    def counting_put(*a, **k):
        calls["puts"] += 1
        return real_put(*a, **k)

    monkeypatch.setattr(sup, "put", counting_put)
    d = MicroBatchDispatcher(registry, background=False)
    out = d.serve("a", "predict", _requests(fitted, n=1)[0])
    slo = d.close()
    assert out is not None and calls["puts"] == 0
    assert slo["degraded"] == 1
    breaker.reset("test: open-breaker leg done")


# -- result cache ------------------------------------------------------------


def test_transform_cache_hits_and_kill_switch(registry, fitted,
                                              monkeypatch):
    obs.enable()
    rec = obs.get_recorder()
    s0 = serve_cache.stats()
    r = _requests(fitted, n=1)[0]
    d = MicroBatchDispatcher(registry, background=False)
    first = d.serve("a", "transform", r)
    assert serve_cache.stats()["misses"] == s0["misses"] + 1
    second = d.serve("a", "transform", r)
    assert serve_cache.stats()["hits"] == s0["hits"] + 1
    assert np.array_equal(first, second)
    # predict is stochastic-capable: never cached
    d.serve("a", "predict", r)
    d.serve("a", "predict", r)
    assert serve_cache.stats()["hits"] == s0["hits"] + 1
    d.close()
    # tallies are pre-aggregated: close() flushed them into the obs
    # counters as deltas, not one JSONL line per lookup
    assert rec.counters.get("serving.cache_hits", 0) >= 1
    assert rec.counters.get("serving.cache_misses", 0) >= 1
    # kill switch
    monkeypatch.setenv("SQ_SERVE_CACHE", "0")
    serve_cache.clear()
    s1 = serve_cache.stats()
    d = MicroBatchDispatcher(registry, background=False)
    d.serve("a", "transform", r)
    d.serve("a", "transform", r)
    assert serve_cache.stats() == s1  # disabled: no tallies at all
    d.close()
    obs.disable()


def test_cache_keys_isolate_models_and_payloads(fitted):
    a = ServingModel(fitted["qkm"])
    b = ServingModel(fitted["svd"])
    r = _requests(fitted, n=1)[0]
    k1 = serve_cache.key_for(a.fingerprint, "transform", r)
    k2 = serve_cache.key_for(b.fingerprint, "transform", r)
    assert k1 != k2
    r2 = r.copy()
    r2[0, 0] += 1.0
    assert serve_cache.key_for(a.fingerprint, "transform", r2) != k1


# -- registry ----------------------------------------------------------------


def test_registry_checkpoint_roundtrip_lru_and_digest_reject(tmp_path,
                                                             fitted):
    paths = {}
    for name, est in (("t0", fitted["qkm"]), ("t1", fitted["svd"]),
                      ("t2", fitted["qkm"])):
        paths[name] = save_estimator(est, str(tmp_path / name))
    reg = ModelRegistry(capacity=2)
    for name, p in paths.items():
        reg.register(name, p)
    m0 = reg.resolve("t0")
    assert reg.resolve("t0") is m0  # LRU hit returns the resident model
    reg.resolve("t1")
    reg.resolve("t2")  # capacity 2: t0 evicted
    assert "t0" not in reg.resident_tenants()
    m0b = reg.resolve("t0")  # cold re-load works
    assert m0b is not m0 and m0b.fingerprint == m0.fingerprint

    # digest verification: corrupt the checkpoint state behind the meta
    state = tmp_path / "t1" / "state.npz"
    blob = bytearray(state.read_bytes())
    blob[-1] ^= 0xFF
    state.write_bytes(bytes(blob))
    reg2 = ModelRegistry(capacity=2)
    reg2.register("t1", paths["t1"])
    with pytest.raises(ValueError, match="stale or corrupt"):
        reg2.resolve("t1")


def test_reregister_evicts_and_rekeys_cache(registry, fitted):
    r = _requests(fitted, n=1)[0]
    d = MicroBatchDispatcher(registry, background=False)
    before = d.serve("a", "transform", r)
    old_fp = registry.resolve("a").fingerprint
    registry.register("a", fitted["svd"])  # new model under the tenant
    assert "a" not in registry.resident_tenants()
    after = d.serve("a", "transform", r)
    assert registry.resolve("a").fingerprint != old_fp
    # the new model's transform is the projection, not center distances
    assert not np.allclose(after, before)
    d.close()


def test_serving_model_rejects_unservable():
    with pytest.raises(TypeError):
        ServingModel(object())


def test_registry_warm_prefetches_cold_loads(tmp_path, fitted):
    """ISSUE 10: warm() loads checkpoint-backed tenants on a thread pool
    so the first request hits a resident model — same digest-verified
    resolve path, LRU accounting included; over-capacity requests are
    skipped (warming them would thrash), and a broken checkpoint reports
    an error without aborting the rest."""
    paths = {n: save_estimator(est, str(tmp_path / n))
             for n, est in (("t0", fitted["qkm"]), ("t1", fitted["svd"]),
                            ("t2", fitted["qkm"]))}
    reg = ModelRegistry(capacity=2)
    for n, p in paths.items():
        reg.register(n, p)
    rec = obs.enable()
    out = reg.warm()
    assert out == {"t0": "skipped_capacity", "t1": "loaded",
                   "t2": "loaded"}
    assert set(reg.resident_tenants()) == {"t1", "t2"}
    assert rec.counters.get("serving.registry_warm_loads", 0) == 2
    loads = rec.counters.get("serving.registry_loads", 0)
    # warm hits: resolving the warmed tenants does no further cold load
    m1 = reg.resolve("t1")
    assert reg.resolve("t1") is m1
    assert rec.counters.get("serving.registry_loads", 0) == loads
    # already-resident tenants report as such on a second warm
    assert reg.warm(["t1", "t2"]) == {"t1": "resident", "t2": "resident"}
    obs.disable()

    # a corrupt checkpoint fails ITS tenant only, loudly at resolve time
    state = tmp_path / "t0" / "state.npz"
    blob = bytearray(state.read_bytes())
    blob[-1] ^= 0xFF
    state.write_bytes(bytes(blob))
    out = reg.warm(["t0", "t1"])
    assert out["t1"] == "resident"
    assert out["t0"].startswith("error:")
    with pytest.raises(ValueError, match="stale or corrupt"):
        reg.resolve("t0")


# -- SLO ---------------------------------------------------------------------


def test_slo_record_schema_valid_and_gating(monkeypatch):
    tr = SloTracker("serving.test", slo_p50_ms=1e4, slo_p99_ms=1e4)
    t0 = tr.note_submit()
    tr.note_batch_done([t0], t0 + 0.001, valid_rows=4, bucket_rows=8,
                       degraded=False)
    obs.enable()
    rec = tr.emit()
    stored = obs.get_recorder().slo_records[-1]
    assert validate_record(stored) == []
    obs.disable()
    assert rec["violated"] is False
    assert rec["requests"] == 1 and rec["batches"] == 1
    assert rec["batch_occupancy"] == 0.5

    tight = SloTracker("serving.test", slo_p50_ms=1e-6, slo_p99_ms=1e-6)
    ts = tight.note_submit()
    tight.note_batch_done([ts], ts + 0.05, 4, 8, False)
    assert tight.emit()["violated"] is True
    monkeypatch.setenv("SQ_SERVE_SLO_STRICT", "1")
    with pytest.raises(SloViolation):
        tight.emit()


def test_percentile_nearest_rank():
    vals = list(range(1, 101))
    assert percentile(vals, 0.50) == 50
    assert percentile(vals, 0.99) == 99
    assert percentile(vals, 1.0) == 100
    assert percentile([7.0], 0.99) == 7.0


def test_slo_env_targets(monkeypatch):
    monkeypatch.setenv("SQ_SERVE_SLO_P99_MS", "123.5")
    tr = SloTracker("serving.test")
    assert tr.slo_p99_ms == 123.5 and tr.slo_p50_ms is None
