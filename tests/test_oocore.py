"""Out-of-core shard store + crash-resumable multi-epoch streaming
(sq_learn_tpu.oocore — ISSUE 8's contract).

Parity discipline (inherited from test_resilience): a fault-injected-
and-recovered, interrupted-and-resumed, or disk-round-tripped
computation must agree with its clean in-RAM twin BIT-FOR-BIT wherever
the design promises it — the shard store's whole point is that moving
the dataset out of RAM changes nothing but residency.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from sq_learn_tpu import obs, oocore, streaming
from sq_learn_tpu.oocore import (ArraySource, EpochPlan, RamBudgetError,
                                 ShardCorruptionError)
from sq_learn_tpu.resilience import faults, supervisor
from sq_learn_tpu.resilience.faults import (InjectedInterrupt,
                                            InjectedReadError)

RNG = np.random.default_rng(7)
#: 2003 rows / small shards: many shards with a ragged tail (the shape
#: discipline of test_streaming, at shard granularity)
X_TALL = (RNG.normal(size=(2003, 16)) + 1.0).astype(np.float32)
SHARD_BYTES = 16 * 1024  # 256 rows/shard -> 8 shards, ragged tail


@pytest.fixture(autouse=True, scope="module")
def _clear_compiled_kernels_after_module():
    """This module streams shard-split shapes through the shared
    streaming kernels; clear the compile caches at module teardown so
    test_streaming's ABSOLUTE cache-size discipline pins (which predate
    this module) still measure only their own sweep when the suite runs
    without SQ_TEST_CLEAR_CACHES (the ROADMAP tier-1 command)."""
    yield
    import jax

    jax.clear_caches()


@pytest.fixture()
def store(tmp_path):
    return oocore.store_from_array(str(tmp_path / "store"), X_TALL,
                                   shard_bytes=SHARD_BYTES)


@pytest.fixture()
def recorder(tmp_path):
    rec = obs.enable(str(tmp_path / "obs.jsonl"))
    yield rec
    obs.disable()


class TestShardStore:
    def test_create_open_roundtrip(self, tmp_path):
        st = oocore.create_synthetic_store(
            str(tmp_path / "syn"), 1500, 12, n_classes=3, seed=9,
            shard_bytes=8 * 1024)
        st2 = oocore.open_store(str(tmp_path / "syn"))
        assert st2.fingerprint == st.fingerprint
        assert st2.shape == (1500, 12) and st2.dtype == np.float32
        np.testing.assert_array_equal(st2.read_rows(0, 1500),
                                      st.read_rows(0, 1500))

    def test_synthetic_rebuild_is_bit_identical(self, tmp_path):
        a = oocore.create_synthetic_store(
            str(tmp_path / "a"), 800, 8, seed=4, shard_bytes=4 * 1024)
        b = oocore.create_synthetic_store(
            str(tmp_path / "b"), 800, 8, seed=4, shard_bytes=4 * 1024)
        assert a.fingerprint == b.fingerprint
        np.testing.assert_array_equal(a.read_rows(0, 800),
                                      b.read_rows(0, 800))

    def test_read_rows_across_shards(self, store):
        # slices spanning 2+ shard boundaries, ragged tail included
        for lo, hi in [(0, 2003), (250, 600), (700, 701), (1900, 2003)]:
            np.testing.assert_array_equal(store.read_rows(lo, hi),
                                          X_TALL[lo:hi])
        np.testing.assert_array_equal(store[250:600], X_TALL[250:600])

    def test_take_gather(self, store):
        idx = np.array([0, 255, 256, 1024, 2002])
        np.testing.assert_array_equal(store.take(idx), X_TALL[idx])

    def test_fingerprint_is_content_complete(self, tmp_path):
        """The satellite pin: an interior mutation the strided
        ``_data_digest`` sample MISSES still changes the store
        fingerprint — the caveat is closed for store-backed passes."""
        Xm = X_TALL.copy()
        sampled = np.unique(np.linspace(0, 2002, num=64, dtype=np.int64))
        row = next(r for r in range(2003) if r not in sampled)
        Xm[row, 3] += 1.0
        assert streaming._data_digest(Xm) == streaming._data_digest(X_TALL)
        a = oocore.store_from_array(str(tmp_path / "a"), X_TALL,
                                    shard_bytes=SHARD_BYTES)
        b = oocore.store_from_array(str(tmp_path / "b"), Xm,
                                    shard_bytes=SHARD_BYTES)
        assert a.fingerprint != b.fingerprint

    def test_on_disk_corruption_quarantines_and_raises(self, store):
        # flip bytes INSIDE shard 2's data region on disk: every re-read
        # sees the same rot, so the bounded re-read must exhaust and
        # surface with provenance
        path = store._shard_path(2)
        with open(path, "r+b") as fh:
            fh.seek(-16, os.SEEK_END)
            fh.write(b"\xff" * 16)
        with pytest.raises(ShardCorruptionError, match="shard 2"):
            store.read_shard(2)
        assert 2 in store.quarantined

    def test_verify_off_trusts_bytes(self, store, monkeypatch):
        path = store._shard_path(1)
        with open(path, "r+b") as fh:
            fh.seek(-16, os.SEEK_END)
            fh.write(b"\xff" * 16)
        monkeypatch.setenv("SQ_OOC_VERIFY", "off")
        store.read_shard(1)  # no CRC pass, no raise — documented opt-out

    def test_ram_budget_guard(self, store, monkeypatch):
        monkeypatch.setenv("SQ_OOC_RAM_BUDGET_BYTES",
                           str(store.nbytes // 4))
        with pytest.raises(RamBudgetError):
            store.read_rows(0, store.shape[0])
        # shard-sized reads stay under the budget and work
        np.testing.assert_array_equal(store.read_shard(0),
                                      X_TALL[:store.shard_sizes[0]])

    def test_store_slicing_rejects_gather_keys(self, store):
        with pytest.raises(TypeError):
            store[np.array([1, 2, 3])]


class TestReadFaults:
    def test_transient_read_failure_recovers_with_parity(self, store,
                                                         recorder):
        faults.arm("read_fail:tiles=1,times=1")
        try:
            arr = store.read_shard(1)
        finally:
            plan = faults.disarm()
            supervisor.breaker.reset("test teardown")
        assert any(ev["kind"] == "read_fail" for ev in plan.events)
        np.testing.assert_array_equal(
            arr, X_TALL[store.shard_sizes[0]:2 * store.shard_sizes[0]])
        assert recorder.counters.get("resilience.retries", 0) >= 1

    def test_read_failures_exhaust_to_terminal(self, store, monkeypatch):
        monkeypatch.setenv("SQ_RETRY_MAX", "2")
        monkeypatch.setenv("SQ_RETRY_BACKOFF_S", "0.001")
        faults.arm("read_fail:tiles=0,times=10")
        try:
            with pytest.raises(InjectedReadError):
                store.read_shard(0)
        finally:
            faults.disarm()
            supervisor.breaker.reset("test teardown")

    def test_corrupt_shard_quarantine_then_reread_recovers(self, store,
                                                           recorder):
        faults.arm("corrupt_shard:tiles=3,times=1")
        try:
            arr = store.read_shard(3)
        finally:
            plan = faults.disarm()
        assert any(ev["kind"] == "corrupt_shard" for ev in plan.events)
        lo = 3 * store.shard_sizes[0]
        np.testing.assert_array_equal(
            arr, X_TALL[lo:lo + store.shard_sizes[3]])
        assert 3 not in store.quarantined  # recovered -> unquarantined
        assert recorder.counters.get("oocore.crc_failures", 0) >= 1
        assert recorder.counters.get("oocore.rereads", 0) >= 1

    def test_persistent_corruption_exhausts_rereads(self, store,
                                                    monkeypatch):
        monkeypatch.setenv("SQ_OOC_REREAD_MAX", "1")
        faults.arm("corrupt_shard:tiles=0,times=10")
        try:
            with pytest.raises(ShardCorruptionError):
                store.read_shard(0)
        finally:
            faults.disarm()
        assert 0 in store.quarantined

    def test_read_stall_past_deadline_feeds_breaker(self, store,
                                                    monkeypatch):
        monkeypatch.setenv("SQ_TILE_DEADLINE_S", "0.01")
        supervisor.breaker.reset("test setup")
        faults.arm("read_stall:tiles=0,times=1,s=0.05")
        try:
            store.read_shard(0)  # data arrives, but counts as a timeout
            assert supervisor.breaker.consecutive_failures >= 1
        finally:
            faults.disarm()
            supervisor.breaker.reset("test teardown")

    def test_stream_fold_over_store_absorbs_read_faults(self, store):
        from sq_learn_tpu.streaming import streamed_centered_gram

        _, G_ref, _ = streamed_centered_gram(X_TALL, max_bytes=32 * 1024)
        faults.arm("read_fail:tiles=2,times=1;corrupt_shard:tiles=4,times=1")
        try:
            _, G, _ = streamed_centered_gram(store, max_bytes=32 * 1024)
        finally:
            faults.disarm()
            supervisor.breaker.reset("test teardown")
        np.testing.assert_array_equal(np.asarray(G), np.asarray(G_ref))


class TestEpochEngine:
    def test_epoch_covers_every_row_exactly_once(self, store):
        plan = EpochPlan(seed=3, batch_rows=300)
        for epoch in (0, 1):
            seen = np.concatenate(
                [b[:, 0] for _, b in plan.iter_batches(store, epoch)])
            assert seen.shape[0] == 2003
            np.testing.assert_array_equal(np.sort(seen),
                                          np.sort(X_TALL[:, 0]))

    def test_epochs_shuffle_differently(self, store):
        plan = EpochPlan(seed=3, batch_rows=300)
        b0 = next(iter(plan.iter_batches(store, 0)))[1]
        b1 = next(iter(plan.iter_batches(store, 1)))[1]
        assert not np.array_equal(b0, b1)

    def test_resume_replays_identical_batches(self, store):
        plan = EpochPlan(seed=5, batch_rows=256)
        full = [b for _, b in plan.iter_batches(store, 2)]
        tail = [b for _, b in plan.iter_batches(store, 2, start_batch=4)]
        assert len(tail) == len(full) - 4
        for a, b in zip(full[4:], tail):
            np.testing.assert_array_equal(a, b)

    def test_disk_vs_ram_source_fit_bit_parity(self, store):
        kw = dict(n_clusters=5, batch_rows=256, max_epochs=3, seed=11)
        disk = oocore.minibatch_epoch_fit(store, **kw)
        ram = oocore.minibatch_epoch_fit(
            ArraySource(X_TALL, shard_rows=store.shard_sizes[0]), **kw)
        np.testing.assert_array_equal(disk["centers"], ram["centers"])
        np.testing.assert_array_equal(disk["counts"], ram["counts"])

    def test_interrupt_then_resume_bitwise_parity(self, store, tmp_path,
                                                  monkeypatch):
        monkeypatch.setenv("SQ_STREAM_CKPT_EVERY", "2")
        ck = str(tmp_path / "mb.npz")
        kw = dict(n_clusters=4, batch_rows=256, max_epochs=3, seed=1)
        ref = oocore.minibatch_epoch_fit(store, **kw)
        faults.arm("abort:tile=9,times=1")  # mid-epoch-2
        try:
            with pytest.raises(InjectedInterrupt):
                oocore.minibatch_epoch_fit(store, checkpoint=ck, **kw)
        finally:
            faults.disarm()
        assert os.path.exists(ck)
        out = oocore.minibatch_epoch_fit(store, checkpoint=ck, **kw)
        assert out["resumed_from"] >= 1
        np.testing.assert_array_equal(out["centers"], ref["centers"])
        np.testing.assert_array_equal(out["counts"], ref["counts"])
        # a finished fit cleans up its snapshots, fallback copy included
        assert not os.path.exists(ck) and not os.path.exists(ck + ".prev")

    def test_mutated_store_invalidates_checkpoint(self, store, tmp_path,
                                                  monkeypatch):
        monkeypatch.setenv("SQ_STREAM_CKPT_EVERY", "2")
        ck = str(tmp_path / "mb.npz")
        kw = dict(n_clusters=4, batch_rows=256, max_epochs=2, seed=1)
        faults.arm("abort:tile=5,times=1")
        try:
            with pytest.raises(InjectedInterrupt):
                oocore.minibatch_epoch_fit(store, checkpoint=ck, **kw)
        finally:
            faults.disarm()
        # same data, different shard split -> different fingerprint ->
        # the stale snapshot must be ignored, not resumed
        store2 = oocore.store_from_array(
            str(tmp_path / "resharded"), X_TALL,
            shard_bytes=2 * SHARD_BYTES)
        out = oocore.minibatch_epoch_fit(store2, checkpoint=ck, **kw)
        assert out["resumed_from"] == 0


class TestEstimatorSurfaces:
    def test_minibatch_store_fit_matches_source_twin(self, store):
        from sq_learn_tpu.models import MiniBatchQKMeans

        kw = dict(n_clusters=5, batch_size=256, max_iter=3,
                  random_state=3)
        with pytest.warns(UserWarning, match="classic"):
            disk = MiniBatchQKMeans(**kw).fit(store)
        with pytest.warns(UserWarning, match="classic"):
            mem = MiniBatchQKMeans(**kw).fit(
                ArraySource(X_TALL, shard_rows=store.shard_sizes[0]))
        np.testing.assert_array_equal(disk.cluster_centers_,
                                      mem.cluster_centers_)
        assert disk.n_steps_ == mem.n_steps_ > 0
        assert disk.labels_.shape == (2003,)
        # the epoch engine must land in the same quality regime as the
        # in-RAM padded-shuffle fit (different schedule: not bitwise)
        with pytest.warns(UserWarning, match="classic"):
            ram = MiniBatchQKMeans(**kw).fit(X_TALL)
        assert disk.inertia_ <= 1.5 * ram.inertia_

    def test_minibatch_store_delta_means(self, store):
        from sq_learn_tpu.models import MiniBatchQKMeans

        est = MiniBatchQKMeans(n_clusters=4, batch_size=256, max_iter=2,
                               random_state=0, delta=0.4).fit(store)
        assert est.cluster_centers_.shape == (4, 16)
        assert np.isfinite(est.inertia_)

    def test_minibatch_store_rejects_unsupported(self, store):
        from sq_learn_tpu.models import MiniBatchQKMeans

        with pytest.raises(ValueError, match="sample_weight"):
            MiniBatchQKMeans(n_clusters=3).fit(
                store, sample_weight=np.ones(2003))
        with pytest.raises(ValueError, match="IPE"):
            MiniBatchQKMeans(n_clusters=3, delta=0.2,
                             true_distance_estimate=True).fit(store)

    def test_minibatch_partial_fit_epochs_over_store(self, store):
        from sq_learn_tpu.models import MiniBatchQKMeans

        est = MiniBatchQKMeans(n_clusters=4, batch_size=256,
                               random_state=0)
        est.partial_fit(store)
        steps1 = est.n_steps_
        c1 = est.cluster_centers_.copy()
        est.partial_fit(store)
        assert est.n_steps_ == 2 * steps1
        assert not np.array_equal(c1, est.cluster_centers_)
        assert est.predict(X_TALL[:7]).shape == (7,)

    def test_qpca_store_fit_bit_matches_streamed_array(self, store):
        from sq_learn_tpu.models import QPCA

        disk = QPCA(n_components=3, random_state=0).fit(store)
        assert disk.ingest_ == "streamed"
        ram = QPCA(n_components=3, random_state=0, svd_solver="full",
                   ingest="streamed").fit(X_TALL)
        np.testing.assert_array_equal(disk.components_, ram.components_)
        np.testing.assert_array_equal(disk.singular_values_,
                                      ram.singular_values_)
        np.testing.assert_array_equal(disk.left_sv, ram.left_sv)
        assert disk.transform(X_TALL[:5]).shape == (5, 3)

    def test_qpca_store_rejects_structural_misfits(self, store):
        from sq_learn_tpu.models import QPCA

        with pytest.raises(ValueError, match="partial-U Gram route"):
            # mu(A) needs the resident centered matrix
            QPCA(n_components=3, random_state=0).fit(
                store, theta_estimate=True, eps=0.1)
        with pytest.raises(ValueError, match="monolithic"):
            QPCA(n_components=3, ingest="monolithic",
                 random_state=0).fit(store)


@pytest.mark.slow
class TestKillResume:
    def test_sigkill_mid_epoch_then_resume_bit_parity(self, tmp_path):
        """The acceptance pin: a REAL SIGKILL (not an in-process
        exception) mid-epoch, then a clean rerun that must resume from
        the mid-epoch checkpoint and finish bit-identical to an
        uninterrupted fit."""
        from sq_learn_tpu.oocore.smoke import FIT, STORE

        store_path = str(tmp_path / "store")
        store = oocore.create_synthetic_store(
            store_path, shard_bytes=64 * 1024, **STORE)
        reference = oocore.minibatch_epoch_fit(store, **FIT)

        ckpt_dir = str(tmp_path / "ckpt")
        os.makedirs(ckpt_dir)
        out_path = str(tmp_path / "resumed.npz")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   SQ_STREAM_CKPT_DIR=ckpt_dir, SQ_STREAM_CKPT_EVERY="2",
                   SQ_FAULTS="read_stall:p=1,s=0.1,times=999")
        cmd = [sys.executable, "-m", "sq_learn_tpu.oocore.smoke",
               "--child", store_path, out_path]
        child = subprocess.Popen(cmd, env=env,
                                 stdout=subprocess.DEVNULL,
                                 stderr=subprocess.DEVNULL)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and child.poll() is None:
            if any(f.endswith(".npz") and not f.endswith(".tmp.npz")
                   for f in os.listdir(ckpt_dir)):
                break
            time.sleep(0.01)
        assert child.poll() is None, \
            "child finished before the kill (stalls too short)"
        child.send_signal(signal.SIGKILL)
        assert child.wait() == -signal.SIGKILL
        assert any(f.endswith(".npz") for f in os.listdir(ckpt_dir))
        assert not os.path.exists(out_path)

        env.pop("SQ_FAULTS")
        rc = subprocess.run(cmd, env=env, timeout=600).returncode
        assert rc == 0
        with np.load(out_path, allow_pickle=False) as npz:
            assert int(npz["resumed_from"]) >= 1
            np.testing.assert_array_equal(npz["centers"],
                                          reference["centers"])
            np.testing.assert_array_equal(npz["counts"],
                                          reference["counts"])
        assert not os.listdir(ckpt_dir)


class TestProbeCacheAtomicity:
    def test_concurrent_writers_never_expose_partial_json(self, tmp_path,
                                                          monkeypatch):
        """The satellite pin: the cross-process probe-TTL cache is
        written via fsynced tmp + atomic rename, so a reader racing any
        number of writers sees only complete JSON documents."""
        import json
        import threading

        from sq_learn_tpu.obs import probe as probe_mod

        cache = str(tmp_path / "probe_cache.json")
        monkeypatch.setenv("SQ_PROBE_CACHE", cache)
        stop = threading.Event()
        bad = []

        def writer(tag):
            i = 0
            while not stop.is_set():
                probe_mod._cache_write("ok", 0.001 * i, f"plat-{tag}-{i}")
                i += 1

        def reader():
            while not stop.is_set():
                try:
                    with open(cache) as fh:
                        json.load(fh)
                except FileNotFoundError:
                    pass
                except ValueError as exc:  # partial JSON observed
                    bad.append(str(exc))

        threads = ([threading.Thread(target=writer, args=(t,))
                    for t in range(3)]
                   + [threading.Thread(target=reader) for _ in range(2)])
        for t in threads:
            t.start()
        time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join()
        assert not bad, f"torn cache reads observed: {bad[:3]}"


class TestPrefetch:
    """ISSUE 10: the bounded shard-readahead prefetcher. Depth 0 IS the
    serial path; any depth produces bit-identical results under the full
    read-fault matrix, never reads a skipped shard, respects the RAM
    budget, and surfaces worker-side errors at the shard they belong to."""

    def _depth(self, monkeypatch, d):
        monkeypatch.setenv("SQ_OOC_PREFETCH_DEPTH", str(d))

    def test_engine_depth_parity(self, store, monkeypatch):
        kw = dict(n_clusters=5, batch_rows=256, max_epochs=3, seed=11)
        self._depth(monkeypatch, 0)
        serial = oocore.minibatch_epoch_fit(store, **kw)
        self._depth(monkeypatch, 3)
        deep = oocore.minibatch_epoch_fit(store, **kw)
        np.testing.assert_array_equal(serial["centers"], deep["centers"])
        np.testing.assert_array_equal(serial["counts"], deep["counts"])

    def test_stream_fold_depth_parity(self, store, monkeypatch):
        from sq_learn_tpu.streaming import streamed_centered_gram

        self._depth(monkeypatch, 0)
        _, G0, _ = streamed_centered_gram(store, max_bytes=32 * 1024)
        self._depth(monkeypatch, 2)
        _, G2, _ = streamed_centered_gram(store, max_bytes=32 * 1024)
        np.testing.assert_array_equal(np.asarray(G0), np.asarray(G2))

    def test_estimator_depth_parity(self, store, monkeypatch):
        from sq_learn_tpu.models import QPCA, MiniBatchQKMeans

        kw = dict(n_clusters=5, batch_size=256, max_iter=3, random_state=3)
        self._depth(monkeypatch, 0)
        with pytest.warns(UserWarning, match="classic"):
            mb0 = MiniBatchQKMeans(**kw).fit(store)
        q0 = QPCA(n_components=3, random_state=0).fit(store)
        self._depth(monkeypatch, 3)
        with pytest.warns(UserWarning, match="classic"):
            mb3 = MiniBatchQKMeans(**kw).fit(store)
        q3 = QPCA(n_components=3, random_state=0).fit(store)
        np.testing.assert_array_equal(mb0.cluster_centers_,
                                      mb3.cluster_centers_)
        np.testing.assert_array_equal(mb0.labels_, mb3.labels_)
        np.testing.assert_array_equal(q0.components_, q3.components_)
        np.testing.assert_array_equal(q0.singular_values_,
                                      q3.singular_values_)

    def test_fault_matrix_under_prefetch(self, store, recorder,
                                         monkeypatch):
        """read_fail (worker retry), corrupt_shard (worker quarantine +
        bounded re-read) with depth >= 2: absorbed bit-for-bit."""
        kw = dict(n_clusters=4, batch_rows=256, max_epochs=2, seed=1)
        self._depth(monkeypatch, 0)
        ref = oocore.minibatch_epoch_fit(store, **kw)
        self._depth(monkeypatch, 3)
        faults.arm("read_fail:tiles=1,times=1;"
                   "corrupt_shard:tiles=3,times=1")
        try:
            out = oocore.minibatch_epoch_fit(
                oocore.open_store(store.path), **kw)
        finally:
            plan = faults.disarm()
            supervisor.breaker.reset("test teardown")
        kinds = {ev["kind"] for ev in plan.events}
        assert {"read_fail", "corrupt_shard"} <= kinds
        np.testing.assert_array_equal(out["centers"], ref["centers"])
        assert recorder.counters.get("oocore.rereads", 0) >= 1
        assert recorder.counters.get("resilience.retries", 0) >= 1
        assert recorder.counters.get("oocore.prefetch_hits", 0) \
            + recorder.counters.get("oocore.prefetch_stalls", 0) >= 1

    def test_worker_read_stall_feeds_breaker_thread_safely(
            self, store, monkeypatch):
        """Stalling reads on PREFETCH WORKERS count breaker timeouts
        exactly like consumer-thread reads (the feed is now locked):
        every shard read stalls past the deadline, so the consecutive
        count crosses K and the breaker trips — fed from two worker
        threads concurrently without losing a count."""
        from sq_learn_tpu.oocore.prefetch import iter_shards

        monkeypatch.setenv("SQ_TILE_DEADLINE_S", "0.01")
        supervisor.breaker.reset("test setup")
        trips0 = supervisor.breaker.trips
        faults.arm("read_stall:p=1,s=0.05,times=1")
        try:
            arrs = list(iter_shards(store, range(store.n_shards),
                                    depth=3, threads=2))
            assert supervisor.breaker.trips > trips0, (
                "worker-thread timeouts never tripped the breaker")
        finally:
            faults.disarm()
            supervisor.breaker.reset("test teardown")
        for i, arr in enumerate(arrs):  # the data still arrived, intact
            lo = int(store._offsets[i])
            np.testing.assert_array_equal(
                arr, X_TALL[lo:lo + store.shard_sizes[i]])

    def test_worker_error_surfaces_at_owner_shard(self, store,
                                                  monkeypatch):
        """Persistent corruption of shard 3 raises ShardCorruptionError
        with shard-3 provenance AT position 3 — after shards 0-2 served."""
        from sq_learn_tpu.oocore.prefetch import iter_shards

        monkeypatch.setenv("SQ_OOC_REREAD_MAX", "1")
        faults.arm("corrupt_shard:tiles=3,times=10")
        got = []
        try:
            with pytest.raises(ShardCorruptionError, match="shard 3"):
                for arr in iter_shards(store, range(store.n_shards),
                                       depth=3, threads=2):
                    got.append(arr)
        finally:
            faults.disarm()
        assert len(got) == 3  # shards 0..2 served before the error
        for i, arr in enumerate(got):
            lo = int(store._offsets[i])
            np.testing.assert_array_equal(
                arr, X_TALL[lo:lo + store.shard_sizes[i]])

    def test_skipped_shards_never_read(self, store, monkeypatch):
        """Epoch-plan awareness: a resume that skips leading shards must
        not prefetch them either."""
        self._depth(monkeypatch, 3)
        plan = EpochPlan(seed=5, batch_rows=256)
        full = [b for _, b in plan.iter_batches(store, 2)]

        reads = []
        real = oocore.ShardStore.read_shard

        def spy_read(self, i):
            reads.append(int(i))
            return real(self, i)

        monkeypatch.setattr(oocore.ShardStore, "read_shard", spy_read)
        tail = [b for _, b in plan.iter_batches(store, 2, start_batch=4)]
        # bit parity of the replayed suffix
        assert len(tail) == len(full) - 4
        for a, b in zip(full[4:], tail):
            np.testing.assert_array_equal(a, b)
        # 4 batches * 256 rows skip the first 1024 rows: the shards
        # wholly inside that prefix must never have been read
        skipped, skip = [], 4 * 256
        for s in plan.shard_order(store, 2):
            if skip >= store.shard_sizes[int(s)]:
                skipped.append(int(s))
                skip -= store.shard_sizes[int(s)]
            else:
                break
        assert skipped, "test store too small to skip a whole shard"
        assert reads, "spy never saw a read (prefetch bypassed it?)"
        assert not (set(reads) & set(skipped)), (
            f"prefetcher read skipped shards {set(reads) & set(skipped)}")

    def test_host_partition_never_reads_foreign_shards(self, store,
                                                       monkeypatch):
        """ISSUE 18: a host walking its ``host_partition`` slice with
        readahead armed touches ONLY its owned shards — peers' shards
        and (on a post-shrink resume) the already-folded prefix are
        never read, not even speculatively by the prefetch workers."""
        from sq_learn_tpu.oocore.prefetch import iter_shards

        self._depth(monkeypatch, 3)
        plan = EpochPlan(seed=5)
        mine = plan.host_partition(store, 1, 3, 2)
        foreign = set(range(store.n_shards)) - {s for _, s in mine}

        reads = []
        real = oocore.ShardStore.read_shard

        def spy_read(self, i):
            reads.append(int(i))
            return real(self, i)

        monkeypatch.setattr(oocore.ShardStore, "read_shard", spy_read)
        arrs = list(iter_shards(store, [s for _, s in mine]))
        for (_, s), arr in zip(mine, arrs):  # right shards, right order
            lo = int(store._offsets[s])
            np.testing.assert_array_equal(
                arr, X_TALL[lo:lo + store.shard_sizes[s]])
        assert set(reads) == {s for _, s in mine}
        assert not (set(reads) & foreign)

        # resume-after-shrink: repartition at 2 hosts from a committed
        # cursor — the folded prefix's shards stay untouched
        reads.clear()
        cursor = 4
        resumed = plan.host_partition(store, 1, 2, 1, start_pos=cursor)
        folded = {int(plan.shard_order(store, 1)[p])
                  for p in range(cursor)}
        list(iter_shards(store, [s for _, s in resumed]))
        assert reads, "spy never saw a read"
        assert not (set(reads) & (folded - {s for _, s in resumed})), (
            "prefetcher re-read folded shards across the shrink")

    def test_ram_budget_bounds_readahead(self, store, monkeypatch):
        """With a budget barely above two shards, readahead degrades
        toward serial but still completes with parity (the consumer's
        own position is always allowed to claim)."""
        from sq_learn_tpu.oocore.prefetch import ShardPrefetcher

        shard_b = store.shard_sizes[0] * 16 * 4
        monkeypatch.setenv("SQ_OOC_RAM_BUDGET_BYTES", str(3 * shard_b))
        pf = ShardPrefetcher(store, range(store.n_shards), depth=4,
                             threads=2)
        try:
            assert pf._avail is not None and pf._avail <= shard_b
            for pos in range(store.n_shards):
                arr = pf.get(pos)
                lo = int(store._offsets[pos])
                np.testing.assert_array_equal(
                    arr, X_TALL[lo:lo + store.shard_sizes[pos]])
        finally:
            pf.close()

    def test_sequential_contract_and_close(self, store):
        from sq_learn_tpu.oocore.prefetch import ShardPrefetcher

        pf = ShardPrefetcher(store, [0, 1, 2], depth=2, threads=2)
        try:
            pf.get(0)
            with pytest.raises(RuntimeError, match="sequential"):
                pf.get(2)
        finally:
            pf.close()
        pf.close()  # idempotent

    def test_prefetched_view_serves_row_walks(self, store, monkeypatch):
        self._depth(monkeypatch, 2)
        view = store.prefetched()
        assert view is not store
        try:
            np.testing.assert_array_equal(view.read_rows(300, 900),
                                          X_TALL[300:900])
            np.testing.assert_array_equal(view.read_rows(900, 2003),
                                          X_TALL[900:2003])
            assert view.fingerprint == store.fingerprint
            assert streaming.is_row_source(view)
        finally:
            view.close()
        self._depth(monkeypatch, 0)
        assert store.prefetched() is store  # depth 0: no wrapper

    def test_prefetch_counters_and_span(self, store, recorder,
                                        monkeypatch):
        self._depth(monkeypatch, 2)
        kw = dict(n_clusters=4, batch_rows=256, max_epochs=1, seed=0)
        oocore.minibatch_epoch_fit(store, **kw)
        gets = (recorder.counters.get("oocore.prefetch_hits", 0)
                + recorder.counters.get("oocore.prefetch_stalls", 0))
        assert gets == store.n_shards  # one epoch visits every shard once
        assert any(s["name"] == "oocore.prefetch" for s in recorder.spans)


class TestAsyncCheckpoints:
    """ISSUE 10: mid-epoch snapshots move to a background writer thread —
    same save_stream_state durability, zero batch-loop stall, drain-
    before-delete so a finished fit can never be resurrected."""

    def test_async_writer_drains_and_loads(self, tmp_path):
        from sq_learn_tpu.utils.checkpoint import (AsyncStreamCheckpointer,
                                                   load_stream_state)

        path = str(tmp_path / "ck.npz")
        w = AsyncStreamCheckpointer(path)
        tpl = {"a": np.zeros(3, np.float32)}
        for cursor in range(1, 6):
            w.submit({"a": np.full(3, cursor, np.float32)}, cursor, "fp")
        w.close()
        assert w.writes >= 1
        assert w.writes + w.dropped == 5  # every submit written or
        # superseded by a newer one (latest-wins)
        loaded = load_stream_state(path, tpl, "fp")
        assert loaded is not None
        acc, cursor = loaded
        # the LAST submitted snapshot is what survives
        assert cursor == 5
        np.testing.assert_array_equal(acc["a"], np.full(3, 5, np.float32))

    def test_async_writer_snapshot_isolated_from_mutation(self, tmp_path):
        """submit() deep-copies: mutating the live state after submit
        must not corrupt the written snapshot."""
        from sq_learn_tpu.utils.checkpoint import (AsyncStreamCheckpointer,
                                                   load_stream_state)

        path = str(tmp_path / "ck.npz")
        w = AsyncStreamCheckpointer(path)
        state = {"step": np.zeros((), np.int64)}
        w.submit(state, 1, "fp")
        state["step"] += 41  # in-place mutation after the snapshot
        w.close()
        acc, _ = load_stream_state(path, state, "fp")
        assert int(acc["step"]) == 0

    def test_async_writer_error_surfaces(self, tmp_path):
        from sq_learn_tpu.utils.checkpoint import AsyncStreamCheckpointer

        w = AsyncStreamCheckpointer(str(tmp_path / "no_dir" / "ck.npz"))
        w.submit({"a": np.zeros(2)}, 1, "fp")
        with pytest.raises(Exception):
            w.close()

    def test_interrupt_resume_parity_serial_ckpt_mode(self, store,
                                                      tmp_path,
                                                      monkeypatch):
        """SQ_OOC_ASYNC_CKPT=0 restores the synchronous write path —
        parity and cleanup contracts identical (the default async mode
        is covered by the pre-existing interrupt/resume + SIGKILL tests)."""
        monkeypatch.setenv("SQ_OOC_ASYNC_CKPT", "0")
        monkeypatch.setenv("SQ_STREAM_CKPT_EVERY", "2")
        ck = str(tmp_path / "mb.npz")
        kw = dict(n_clusters=4, batch_rows=256, max_epochs=3, seed=1)
        ref = oocore.minibatch_epoch_fit(store, **kw)
        faults.arm("abort:tile=9,times=1")
        try:
            with pytest.raises(InjectedInterrupt):
                oocore.minibatch_epoch_fit(store, checkpoint=ck, **kw)
        finally:
            faults.disarm()
        out = oocore.minibatch_epoch_fit(store, checkpoint=ck, **kw)
        assert out["resumed_from"] >= 1
        np.testing.assert_array_equal(out["centers"], ref["centers"])
        assert not os.path.exists(ck) and not os.path.exists(ck + ".prev")


class TestParallelStoreBuild:
    def test_parallel_build_matches_serial_manifest(self, tmp_path,
                                                    monkeypatch):
        """The thread-pool build must be byte-identical to the serial
        one: same shard files, same CRCs, same fingerprint, same
        float-accumulated column stats (commit order is shard order)."""
        import json

        kw = dict(n_samples=900, n_features=8, n_classes=3, seed=4,
                  shard_bytes=4 * 1024)
        monkeypatch.setenv("SQ_OOC_PREFETCH_THREADS", "3")
        par = oocore.create_synthetic_store(str(tmp_path / "par"), **kw)
        monkeypatch.setenv("SQ_OOC_PREFETCH_THREADS", "1")
        ser = oocore.create_synthetic_store(str(tmp_path / "ser"), **kw)
        assert par.fingerprint == ser.fingerprint
        mp = json.load(open(os.path.join(par.path, "manifest.json")))
        ms = json.load(open(os.path.join(ser.path, "manifest.json")))
        assert mp == ms
        np.testing.assert_array_equal(par.read_rows(0, 900),
                                      ser.read_rows(0, 900))


class TestCodecStore:
    """Compressed shard store (ISSUE 13): the disk representation
    changes, nothing else does — every read surface, the prefetcher,
    the fault matrix, and the fits must be bit-identical to the
    uncompressed twin."""

    @pytest.fixture()
    def cstore(self, tmp_path):
        return oocore.store_from_array(str(tmp_path / "cstore"), X_TALL,
                                       shard_bytes=SHARD_BYTES,
                                       codec="lz4")

    def test_roundtrip_and_manifest(self, cstore, tmp_path):
        assert cstore.codec == "lz4"
        assert cstore.manifest["codec"] == "lz4"
        assert cstore.stored_nbytes < cstore.nbytes
        assert all("stored_bytes" in s for s in cstore.manifest["shards"])
        for lo, hi in [(0, 2003), (250, 600), (700, 701), (1900, 2003)]:
            np.testing.assert_array_equal(cstore.read_rows(lo, hi),
                                          X_TALL[lo:hi])
        idx = np.array([0, 255, 256, 1024, 2002])
        np.testing.assert_array_equal(cstore.take(idx), X_TALL[idx])
        re = oocore.open_store(cstore.path)
        assert re.codec == "lz4"
        np.testing.assert_array_equal(re.read_rows(0, 2003), X_TALL)

    def test_env_default_codec(self, tmp_path, monkeypatch):
        monkeypatch.setenv("SQ_OOC_CODEC", "lz4")
        st = oocore.store_from_array(str(tmp_path / "env"), X_TALL,
                                     shard_bytes=SHARD_BYTES)
        assert st.codec == "lz4" and st.stored_nbytes < st.nbytes
        monkeypatch.setenv("SQ_OOC_CODEC", "zstd")
        with pytest.raises(ValueError, match="SQ_OOC_CODEC"):
            oocore.store_from_array(str(tmp_path / "bad"), X_TALL)

    def test_uncompressed_manifest_has_no_codec_field(self, store):
        # the pre-codec layout is untouched: codec "none" writes the
        # exact old manifest (no codec key, no stored_bytes) and old
        # stores keep loading bit-identically
        assert store.codec == "none"
        assert "codec" not in store.manifest
        assert all("stored_bytes" not in s
                   for s in store.manifest["shards"])
        assert store.stored_nbytes == store.nbytes

    def test_unknown_codec_refused(self, cstore):
        import json

        man = json.load(open(os.path.join(cstore.path, "manifest.json")))
        man["codec"] = "zstd"
        json.dump(man, open(os.path.join(cstore.path, "manifest.json"),
                            "w"))
        with pytest.raises(ValueError, match="unknown codec"):
            oocore.open_store(cstore.path)

    def test_engine_and_estimator_parity_vs_uncompressed(self, store,
                                                         cstore):
        from sq_learn_tpu.models import MiniBatchQKMeans

        a = oocore.minibatch_epoch_fit(store, n_clusters=5,
                                       batch_rows=256, max_epochs=2,
                                       seed=3)
        b = oocore.minibatch_epoch_fit(cstore, n_clusters=5,
                                       batch_rows=256, max_epochs=2,
                                       seed=3)
        np.testing.assert_array_equal(a["centers"], b["centers"])
        np.testing.assert_array_equal(a["counts"], b["counts"])
        kw = dict(n_clusters=4, batch_size=512, max_iter=2, tol=0.0,
                  n_init=1, max_no_improvement=None, compute_labels=False,
                  random_state=0)
        ea = MiniBatchQKMeans(**kw).fit(store)
        eb = MiniBatchQKMeans(**kw).fit(cstore)
        np.testing.assert_array_equal(ea.cluster_centers_,
                                      eb.cluster_centers_)

    def test_prefetched_fault_matrix_parity(self, store, cstore,
                                            monkeypatch):
        """read_fail + corrupt_shard over the compressed store at depth
        3: retries, quarantine, bounded re-read and the decode all run
        on worker threads, bit-identical to the serial uncompressed
        walk."""
        monkeypatch.setenv("SQ_RETRY_BACKOFF_S", "0.001")
        monkeypatch.setenv("SQ_OOC_PREFETCH_DEPTH", "0")
        ref = oocore.minibatch_epoch_fit(store, n_clusters=4,
                                         batch_rows=256, max_epochs=2,
                                         seed=1)
        monkeypatch.setenv("SQ_OOC_PREFETCH_DEPTH", "3")
        plan = faults.arm("read_fail:tiles=2,times=1;"
                          "corrupt_shard:tiles=4,times=1")
        try:
            got = oocore.minibatch_epoch_fit(
                oocore.open_store(cstore.path), n_clusters=4,
                batch_rows=256, max_epochs=2, seed=1)
        finally:
            faults.disarm()
        np.testing.assert_array_equal(ref["centers"], got["centers"])
        kinds = {e["kind"] for e in plan.events}
        assert {"read_fail", "corrupt_shard"} <= kinds

    def test_qpca_gram_route_parity(self, store, cstore):
        """The streamed Gram consumer (prefetched row walks) reads the
        codec store bit-identically."""
        from sq_learn_tpu.streaming import streamed_centered_gram

        _, G_ref, _ = streamed_centered_gram(store, max_bytes=32 * 1024)
        _, G, _ = streamed_centered_gram(cstore, max_bytes=32 * 1024)
        np.testing.assert_array_equal(np.asarray(G), np.asarray(G_ref))

    def test_budget_accounts_compressed_plus_raw(self, cstore,
                                                 monkeypatch):
        from sq_learn_tpu.oocore.prefetch import ShardPrefetcher

        raw = max(int(s) * 16 * 4 for s in cstore.shard_sizes)
        stored = max(cstore.shard_stored_sizes)
        # budget: floor (2 raw shards) + one raw+stored claim, but NOT
        # two — the ledger must stop the second worker's claim
        budget = 2 * raw + (raw + stored) + stored // 2
        monkeypatch.setenv("SQ_OOC_RAM_BUDGET_BYTES", str(budget))
        pf = ShardPrefetcher(cstore, list(range(cstore.n_shards)),
                             depth=4, threads=2)
        try:
            assert pf._extra[0] > 0  # codec stores claim stored+raw
            out = [pf.get(i) for i in range(cstore.n_shards)]
        finally:
            pf.close()
        np.testing.assert_array_equal(np.concatenate(out), X_TALL)

    def test_single_materialization_budget_counts_payload(self, cstore,
                                                          monkeypatch):
        raw_shard = cstore.shard_sizes[0] * 16 * 4
        # enough for the raw array alone but not payload + raw together
        monkeypatch.setenv("SQ_OOC_RAM_BUDGET_BYTES", str(raw_shard + 16))
        with pytest.raises(RamBudgetError):
            cstore.read_shard(0)

    def test_verify_off_decode_error_has_provenance(self, cstore,
                                                    monkeypatch):
        # flip bytes INSIDE the stored payload on disk; with CRC off the
        # decoder is the last line of defense and must surface shard
        # provenance, not crash
        path = cstore._shard_path(1)
        with open(path, "r+b") as fh:
            fh.seek(-16, os.SEEK_END)
            fh.write(b"\xff" * 16)
        monkeypatch.setenv("SQ_OOC_VERIFY", "off")
        with pytest.raises(ShardCorruptionError, match="shard 1"):
            cstore.read_shard(1)

    def test_store_from_array_parallel_build_manifest_parity(
            self, tmp_path, monkeypatch):
        """The ISSUE 13 satellite pin: store_from_array rides the same
        build pool as create_synthetic_store, and its manifest is
        byte-identical to a serial build's — for both codecs."""
        import json

        for codec in ("none", "lz4"):
            monkeypatch.setenv("SQ_OOC_PREFETCH_THREADS", "3")
            par = oocore.store_from_array(
                str(tmp_path / f"par_{codec}"), X_TALL,
                shard_bytes=SHARD_BYTES, codec=codec)
            # window <= 1 forces the strictly serial loop
            monkeypatch.setenv("SQ_OOC_RAM_BUDGET_BYTES",
                               str(3 * SHARD_BYTES))
            ser = oocore.store_from_array(
                str(tmp_path / f"ser_{codec}"), X_TALL,
                shard_bytes=SHARD_BYTES, codec=codec)
            monkeypatch.delenv("SQ_OOC_RAM_BUDGET_BYTES")
            assert par.fingerprint == ser.fingerprint
            mp = json.load(open(os.path.join(par.path, "manifest.json")))
            ms = json.load(open(os.path.join(ser.path, "manifest.json")))
            assert mp == ms

    def test_cold_tier_first_touch_and_bandwidth_model(self, cstore,
                                                       recorder):
        import time

        plan = faults.arm("cold_tier:s=0.03,per_mb=0.5")
        try:
            t0 = time.perf_counter()
            cstore.read_shard(0)
            cold = time.perf_counter() - t0
            t0 = time.perf_counter()
            cstore.read_shard(0)
            warm = time.perf_counter() - t0
        finally:
            faults.disarm()
        events = [e for e in plan.events if e["kind"] == "cold_tier"]
        assert len(events) == 1  # times=1 default: first touch only
        want = 0.03 + 0.5 * (cstore.shard_stored_sizes[0] / 2**20)
        assert events[0]["stall_s"] == pytest.approx(want, rel=1e-4)
        assert cold >= want and warm < want
        assert any(e["kind"] == "cold_tier"
                   for e in recorder.fault_events)

    def test_cold_tier_spec_grammar(self):
        plan = faults.FaultPlan("cold_tier:s=0.01,per_mb=0.2,times=3")
        inj = plan.injectors[0]
        assert (inj.kind, inj.stall_s, inj.per_mb, inj.times) == \
            ("cold_tier", 0.01, 0.2, 3)
        with pytest.raises(faults.FaultSpecError):
            faults.FaultPlan("cold_tier:bad=1")

    def test_codec_counters(self, cstore, recorder):
        cstore.read_shard(0)
        assert recorder.counters.get("oocore.codec_bytes_in", 0) == \
            cstore.shard_stored_sizes[0]
        assert recorder.counters.get("oocore.codec_bytes_out", 0) == \
            cstore.shard_sizes[0] * 16 * 4
