"""Linalg parity tests vs scipy/sklearn ground truth."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.linalg
import scipy.spatial.distance

from sq_learn_tpu.ops.linalg import (
    centered_svd,
    pairwise_sq_distances,
    randomized_svd,
    row_norms,
    smallest_singular_value,
    svd_flip,
    thin_svd,
)


@pytest.fixture
def tall():
    return np.random.RandomState(0).randn(200, 12).astype(np.float32)


@pytest.fixture
def wide():
    return np.random.RandomState(1).randn(10, 80).astype(np.float32)


class TestThinSVD:
    # the gram path squares the condition number: float32 tolerance is looser
    @pytest.mark.parametrize("method,atol", [("gram", 5e-2), ("direct", 2e-3)])
    def test_tall(self, tall, method, atol):
        U, S, Vt = thin_svd(jnp.asarray(tall), method=method)
        S_ref = scipy.linalg.svd(tall, compute_uv=False)
        np.testing.assert_allclose(np.asarray(S), S_ref, rtol=2e-3)
        recon = np.asarray(U) * np.asarray(S) @ np.asarray(Vt)
        np.testing.assert_allclose(recon, tall, atol=atol)

    @pytest.mark.parametrize("method,atol", [("gram", 5e-2), ("direct", 2e-3)])
    def test_wide(self, wide, method, atol):
        U, S, Vt = thin_svd(jnp.asarray(wide), method=method)
        S_ref = scipy.linalg.svd(wide, compute_uv=False)
        np.testing.assert_allclose(np.asarray(S), S_ref, rtol=2e-3)
        recon = np.asarray(U) * np.asarray(S) @ np.asarray(Vt)
        np.testing.assert_allclose(recon, wide, atol=atol)

    def test_orthonormal(self, tall):
        U, S, Vt = thin_svd(jnp.asarray(tall), method="gram")
        np.testing.assert_allclose(
            np.asarray(U.T @ U), np.eye(12), atol=5e-3
        )


class TestCenteredSVD:
    def test_matches_sklearn_pca(self, tall):
        from sklearn.decomposition import PCA

        mean, U, S, Vt = centered_svd(jnp.asarray(tall))
        pca = PCA(svd_solver="full").fit(tall)
        np.testing.assert_allclose(np.asarray(mean), tall.mean(0), atol=1e-5)
        np.testing.assert_allclose(np.asarray(S), pca.singular_values_, rtol=2e-3)
        # components match up to the shared svd_flip sign convention
        np.testing.assert_allclose(
            np.abs(np.asarray(Vt)), np.abs(pca.components_), atol=2e-2
        )


class TestRandomizedSVD:
    def test_recovers_low_rank(self, key):
        rng = np.random.RandomState(2)
        A = (rng.randn(300, 40) @ np.diag(np.geomspace(100, 0.01, 40)) @
             rng.randn(40, 30)).astype(np.float32)
        U, S, Vt = randomized_svd(key, jnp.asarray(A), n_components=10, n_iter=6)
        S_ref = scipy.linalg.svd(A, compute_uv=False)[:10]
        np.testing.assert_allclose(np.asarray(S), S_ref, rtol=1e-2)

    def test_wide_input(self, key):
        A = np.random.RandomState(3).randn(20, 200).astype(np.float32)
        U, S, Vt = randomized_svd(key, jnp.asarray(A), n_components=5, n_iter=6)
        assert U.shape == (20, 5) and Vt.shape == (5, 200)
        S_ref = scipy.linalg.svd(A, compute_uv=False)[:5]
        np.testing.assert_allclose(np.asarray(S), S_ref, rtol=2e-2)


class TestPairwise:
    def test_matches_cdist(self):
        X = np.random.RandomState(4).randn(50, 7).astype(np.float32)
        C = np.random.RandomState(5).randn(4, 7).astype(np.float32)
        d2 = pairwise_sq_distances(jnp.asarray(X), jnp.asarray(C))
        ref = scipy.spatial.distance.cdist(X, C, "sqeuclidean")
        # ‖x‖²+‖c‖²−2x·c cancels catastrophically in float32: ~1% tolerance
        np.testing.assert_allclose(np.asarray(d2), ref, rtol=2e-2, atol=1e-2)

    def test_row_norms(self):
        X = np.random.RandomState(6).randn(30, 5).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(row_norms(jnp.asarray(X), squared=True)),
            (X**2).sum(1),
            rtol=1e-5,
        )


class TestMisc:
    def test_svd_flip_deterministic(self, tall):
        U, S, Vt = thin_svd(jnp.asarray(tall))
        U1, Vt1 = svd_flip(U, Vt)
        U2, Vt2 = svd_flip(-U, -Vt)
        np.testing.assert_allclose(np.asarray(U1), np.asarray(U2), atol=1e-6)

    def test_smallest_singular_value(self, tall):
        ref = scipy.linalg.svd(tall, compute_uv=False)[-1]
        np.testing.assert_allclose(
            float(smallest_singular_value(jnp.asarray(tall))), ref, rtol=5e-2
        )
