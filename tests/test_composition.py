"""Composition layer tests: model_selection, pipeline, preprocessing, KNN —
plus the end-to-end MnistTrial-style quantum pipeline (reference
``MnistTrial.py:10-28`` is the parity target)."""

import numpy as np
import pytest

from sq_learn_tpu import Pipeline, clone, make_pipeline
from sq_learn_tpu.datasets import load_digits, make_blobs
from sq_learn_tpu.model_selection import (
    GridSearchCV,
    KFold,
    StratifiedKFold,
    cross_val_score,
    cross_validate,
    train_test_split,
)
from sq_learn_tpu.models import (
    KMeans,
    KNeighborsClassifier,
    PCA,
    QPCA,
)
from sq_learn_tpu.preprocessing import MinMaxScaler, Normalizer, StandardScaler


@pytest.fixture(scope="module")
def digits():
    return load_digits()


class TestSplitters:
    def test_kfold_partitions(self):
        X = np.arange(23).reshape(-1, 1)
        seen = []
        for train, test in KFold(5).split(X):
            assert len(np.intersect1d(train, test)) == 0
            seen.extend(test.tolist())
        assert sorted(seen) == list(range(23))

    def test_stratified_kfold_balance(self):
        y = np.array([0] * 40 + [1] * 10)
        X = np.zeros((50, 2))
        for train, test in StratifiedKFold(5).split(X, y):
            # each fold holds ~1/5 of each class
            assert np.sum(y[test] == 0) == 8
            assert np.sum(y[test] == 1) == 2

    def test_stratified_kfold_equal_fold_totals(self):
        # upstream's sorted-interleave allocation staggers per-class
        # remainders so TOTAL fold sizes also differ by at most 1 (a
        # per-class round-robin stacks remainders on the low folds)
        rng = np.random.default_rng(3)
        y = rng.integers(0, 3, 121)  # several classes with remainders
        X = np.zeros((121, 2))
        folds = list(StratifiedKFold(4).split(X, y))
        sizes = [len(test) for _, test in folds]
        assert max(sizes) - min(sizes) <= 1, sizes
        # each class's members spread as evenly as possible: per-class
        # fold counts differ by at most 1 across folds
        counts = np.array([np.bincount(y[test], minlength=3)
                           for _, test in folds])
        assert np.all(counts.max(axis=0) - counts.min(axis=0) <= 1), counts

    def test_stratified_kfold_matches_sklearn_splits(self):
        # first-appearance class encoding + sorted-interleave allocation
        # reproduce sklearn's splits index-for-index (shuffle=False)
        from sklearn.model_selection import StratifiedKFold as SKSplit

        rng = np.random.default_rng(1)
        y = rng.choice([7, 2, 9], size=80)  # non-sorted first appearance
        X = np.zeros((80, 2))
        for (_, te1), (_, te2) in zip(StratifiedKFold(4).split(X, y),
                                      SKSplit(4).split(X, y)):
            np.testing.assert_array_equal(np.sort(te1), np.sort(te2))

    def test_stratified_kfold_guards(self):
        # every class smaller than n_splits: error (upstream semantics);
        # least-populated class below n_splits: warning
        with pytest.raises(ValueError, match="number of members"):
            list(StratifiedKFold(3).split(np.zeros((4, 1)), [0, 0, 1, 1]))
        with pytest.warns(UserWarning, match="least populated"):
            list(StratifiedKFold(3).split(np.zeros((10, 1)),
                                          [0] * 8 + [1] * 2))

    def test_train_test_split_stratified(self):
        X = np.arange(100).reshape(-1, 1)
        y = np.array([0] * 80 + [1] * 20)
        X_tr, X_te, y_tr, y_te = train_test_split(
            X, y, test_size=0.25, stratify=y, random_state=0)
        assert len(X_te) == pytest.approx(25, abs=1)
        assert np.mean(y_te) == pytest.approx(0.2, abs=0.05)
        assert len(X_tr) + len(X_te) == 100


class TestCV:
    def test_cross_validate_knn(self, digits):
        X, y = digits
        res = cross_validate(
            KNeighborsClassifier(n_neighbors=5), X[:500], y[:500], cv=3)
        assert len(res["test_score"]) == 3
        assert np.mean(res["test_score"]) > 0.9

    def test_int_cv_stratifies_for_classifiers(self):
        # class-sorted labels: plain KFold would train on one class only
        X, y = make_blobs(n_samples=100, centers=2, n_features=4,
                          cluster_std=0.5, random_state=3)
        order = np.argsort(y)
        X, y = X[order], y[order]
        scores = cross_val_score(
            KNeighborsClassifier(n_neighbors=3), X, y, cv=2)
        assert np.mean(scores) > 0.9

    def test_grid_search(self, digits):
        X, y = digits
        gs = GridSearchCV(
            KNeighborsClassifier(), {"n_neighbors": [1, 5]}, cv=3,
        ).fit(X[:300], y[:300])
        assert gs.best_params_["n_neighbors"] in (1, 5)
        assert 0.8 < gs.best_score_ <= 1.0
        assert gs.predict(X[:10]).shape == (10,)


class TestKNN:
    def test_matches_sklearn(self, digits):
        import sklearn.neighbors

        X, y = digits
        X_tr, X_te = X[:1000], X[1000:1200]
        y_tr = y[:1000]
        ours = KNeighborsClassifier(n_neighbors=5).fit(X_tr, y_tr)
        ref = sklearn.neighbors.KNeighborsClassifier(n_neighbors=5).fit(
            X_tr, y_tr)
        agree = np.mean(ours.predict(X_te) == ref.predict(X_te))
        assert agree > 0.97  # distance ties can break differently

    def test_distance_weights(self, digits):
        X, y = digits
        clf = KNeighborsClassifier(n_neighbors=5, weights="distance").fit(
            X[:500], y[:500])
        proba = clf.predict_proba(X[500:520])
        np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-5)

    def test_kneighbors_output(self, digits):
        X, y = digits
        clf = KNeighborsClassifier(n_neighbors=3).fit(X[:100], y[:100])
        dist, idx = clf.kneighbors(X[:5])
        assert dist.shape == (5, 3)
        # self is the nearest neighbor at distance 0
        np.testing.assert_array_equal(idx[:, 0], np.arange(5))
        np.testing.assert_allclose(dist[:, 0], 0.0, atol=1e-3)


class TestPreprocessing:
    def test_standard_scaler(self, digits):
        X, _ = digits
        Xs = StandardScaler().fit_transform(X)
        np.testing.assert_allclose(Xs.mean(axis=0), 0.0, atol=1e-4)
        active = X.std(axis=0) > 0
        np.testing.assert_allclose(Xs.std(axis=0)[active], 1.0, atol=1e-3)

    def test_minmax_scaler_roundtrip(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(50, 4)).astype(np.float32)
        sc = MinMaxScaler().fit(X)
        Xt = sc.transform(X)
        assert Xt.min() >= -1e-6 and Xt.max() <= 1 + 1e-6
        np.testing.assert_allclose(sc.inverse_transform(Xt), X, atol=1e-5)

    def test_normalizer(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(20, 6)).astype(np.float32)
        Xn = Normalizer().fit_transform(X)
        np.testing.assert_allclose(
            np.linalg.norm(Xn, axis=1), 1.0, atol=1e-5)


class TestPipeline:
    def test_fit_predict_score(self, digits):
        X, y = digits
        pipe = Pipeline([
            ("scale", StandardScaler()),
            ("pca", PCA(n_components=20)),
            ("knn", KNeighborsClassifier(n_neighbors=5)),
        ])
        pipe.fit(X[:800], y[:800])
        assert pipe.score(X[800:1000], y[800:1000]) > 0.85

    def test_nested_params(self):
        pipe = make_pipeline(StandardScaler(), PCA(n_components=5))
        pipe.set_params(pca__n_components=7)
        assert pipe.named_steps["pca"].n_components == 7
        assert pipe.get_params()["pca__n_components"] == 7

    def test_clone_pipeline(self):
        pipe = make_pipeline(StandardScaler(), PCA(n_components=5))
        c = clone(pipe)
        assert c.named_steps["pca"].n_components == 5


class TestMnistTrialPipeline:
    """The reference's flagship experiment (``MnistTrial.py:10-28``):
    qPCA fit → quantum transform with tomography noise → KNN → stratified
    CV — on digits here (MNIST itself is the benchmark, not a unit test)."""

    def test_end_to_end_quantum_pipeline(self, digits):
        X, y = digits
        X, y = X[:600], y[:600]
        # svd_solver='full' forces the quantum path (auto would dispatch
        # >500-sample inputs to the purely-classical randomized solver,
        # exactly as the reference does — _qPCA.py:545-553)
        pca = QPCA(n_components=16, svd_solver="full", random_state=0).fit(
            X, estimate_all=True, eps=0.1, delta=0.1, theta_major=1e-6,
            true_tomography=False)
        # quantum transform onto the tomography-estimated components
        Xq = pca.transform(X, classic_transform=False,
                           use_classical_components=False)
        res = cross_validate(
            KNeighborsClassifier(n_neighbors=7), Xq, y,
            cv=StratifiedKFold(5))
        assert np.mean(res["test_score"]) > 0.85

    def test_parallel_cv_matches_serial(self, digits):
        """n_jobs fans folds over threads (VERDICT r2 missing #5); with a
        fixed random_state each fold fit is deterministic, so the parallel
        results must equal the serial ones fold-for-fold."""
        X, y = digits
        X, y = X[:500], y[:500]
        est = KNeighborsClassifier(n_neighbors=5)
        serial = cross_validate(est, X, y, cv=StratifiedKFold(6),
                                return_train_score=True)
        parallel = cross_validate(est, X, y, cv=StratifiedKFold(6),
                                  n_jobs=4, return_train_score=True)
        np.testing.assert_array_equal(parallel["test_score"],
                                      serial["test_score"])
        np.testing.assert_array_equal(parallel["train_score"],
                                      serial["train_score"])
        assert len(parallel["fit_time"]) == 6

    def test_parallel_cv_rejects_n_jobs_zero(self, digits):
        X, y = digits
        with pytest.raises(ValueError, match="n_jobs == 0"):
            cross_validate(KNeighborsClassifier(3), X[:100], y[:100],
                           cv=StratifiedKFold(2), n_jobs=0)

    def test_parallel_cv_propagates_worker_exception(self, digits):
        """A fold failure inside the thread pool must surface to the
        caller, not vanish into a worker thread."""
        X, y = digits

        class ExplodingKNN(KNeighborsClassifier):
            def fit(self, X, y):
                raise RuntimeError("boom in fold")

        with pytest.raises(RuntimeError, match="boom in fold"):
            cross_validate(ExplodingKNN(3), X[:200], y[:200],
                           cv=StratifiedKFold(4), n_jobs=4)

    def test_parallel_cv_propagates_config_context(self, digits):
        """Worker threads must see the caller's config_context, not the
        global defaults (the config dict is thread-local)."""
        import jax

        from sq_learn_tpu import config_context

        X, y = digits
        X, y = X[:300], y[:300]

        seen_devices = []

        class DeviceProbeKNN(KNeighborsClassifier):
            def fit(self, X, y):
                out = super().fit(X, y)
                seen_devices.append(next(iter(self.X_fit_.devices())))
                return out

        with config_context(device="cpu:3"):
            cross_validate(DeviceProbeKNN(n_neighbors=3), X, y,
                           cv=StratifiedKFold(3), n_jobs=3)
        assert seen_devices and all(
            d == jax.devices("cpu")[3] for d in seen_devices), seen_devices

    def test_noise_degrades_gracefully(self, digits):
        X, y = digits
        X, y = X[:400], y[:400]
        accs = {}
        for eps_delta in (0.05, 0.8):
            pca = QPCA(n_components=16, random_state=0).fit(
                X, estimate_all=True, eps=eps_delta / 2, delta=eps_delta / 2,
                theta_major=1e-6, true_tomography=False)
            Xq = pca.transform(X, classic_transform=False,
                               use_classical_components=False)
            score = np.mean(cross_val_score(
                KNeighborsClassifier(n_neighbors=7), Xq, y,
                cv=StratifiedKFold(3)))
            accs[eps_delta] = score
        assert accs[0.05] >= accs[0.8] - 0.02


class TestDistributed:
    """Single-process checks of the multi-host plumbing layer."""

    def test_process_info_and_mesh(self):
        from sq_learn_tpu.parallel import distributed as dist

        p, n, local = dist.process_info()
        assert p == 0 and n == 1 and local >= 1
        mesh = dist.global_mesh()
        assert mesh.devices.size == local

    def test_host_shard_bounds_cover_dataset(self):
        from sq_learn_tpu.parallel import distributed as dist

        lo, hi, per = dist.host_shard_bounds(1000)
        assert (lo, hi) == (0, 1000)  # single process owns everything
        assert per == 1000


class TestParameterGrid:
    def test_iterates_product(self):
        from sq_learn_tpu.model_selection import ParameterGrid

        grid = ParameterGrid({"a": [1, 2], "b": ["x", "y", "z"]})
        combos = list(grid)
        assert len(combos) == len(grid) == 6
        assert {"a": 2, "b": "z"} in combos

    def test_list_of_grids(self):
        from sq_learn_tpu.model_selection import ParameterGrid

        grid = ParameterGrid([{"a": [1]}, {"b": [2, 3]}])
        assert len(grid) == 3
        assert list(grid) == [{"a": 1}, {"b": 2}, {"b": 3}]


def test_pipeline_predict_proba():
    import numpy as np
    from sq_learn_tpu.datasets import make_blobs
    from sq_learn_tpu.models import KNeighborsClassifier
    from sq_learn_tpu.pipeline import make_pipeline
    from sq_learn_tpu.preprocessing import StandardScaler

    X, y = make_blobs(n_samples=200, centers=3, n_features=5, random_state=0)
    pipe = make_pipeline(StandardScaler(),
                         KNeighborsClassifier(n_neighbors=5)).fit(
        X.astype(np.float32), y)
    proba = pipe.predict_proba(X[:20].astype(np.float32))
    assert proba.shape == (20, 3)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-5)


def test_knn_k_exceeds_train_size_clean_error():
    import numpy as np
    import pytest as _pytest
    from sq_learn_tpu.models import KNeighborsClassifier

    knn = KNeighborsClassifier(n_neighbors=5).fit(
        np.arange(6, dtype=np.float32).reshape(3, 2), np.array([0, 1, 0]))
    with _pytest.raises(ValueError, match="n_neighbors <= n_samples_fit"):
        knn.predict(np.ones((2, 2), np.float32))
    with _pytest.raises(ValueError, match="n_neighbors <= n_samples_fit"):
        knn.kneighbors(np.ones((2, 2), np.float32))
    with _pytest.raises(ValueError, match="positive integer"):
        knn.kneighbors(np.ones((2, 2), np.float32), n_neighbors=0)
    with _pytest.raises(ValueError, match="positive integer"):
        knn.kneighbors(np.ones((2, 2), np.float32), n_neighbors=-1)
