"""Fleet observability (sq_learn_tpu.obs.fleet — ISSUE 19).

The contract under test: per-process obs shards correlated by the
coordinator-minted ``fleet.run_id`` envelope merge into ONE
clock-aligned mesh timeline — NTP-style offsets from the ``clock``
samples the elastic plane piggybacks on its KV exchanges, a monotone
``ts_fleet`` merge, per-generation detect → shrink → re-init → resume
critical paths, and a commit-ledger reconciliation that proves every
committed window appears exactly once. The real multi-process flow is
certified by ``make elastic-smoke``; everything here is hand-built
shards plus the in-process ``elastic_fit_local`` sim.
"""

import gzip
import json
import os

import numpy as np
import pytest

from sq_learn_tpu import obs
from sq_learn_tpu.obs import fleet, report, schema
from sq_learn_tpu.obs import recorder as obs_recorder
from sq_learn_tpu.obs.recorder import Recorder
from sq_learn_tpu.oocore import ArraySource
from sq_learn_tpu.parallel import elastic
from sq_learn_tpu.resilience import faults

V = schema.SCHEMA_VERSION


def _rec(type_, ts, **kw):
    rec = {"v": V, "schema_version": V, "ts": ts, "type": type_}
    rec.update(kw)
    return rec


def _clock(ts, peer, sent, recv, **kw):
    return _rec("clock", ts, peer=peer, sent_ts=sent, recv_ts=recv, **kw)


def _el(ts, event, gen, **kw):
    kw.setdefault("n_hosts", 2)
    return _rec("elastic", ts, event=event, generation=gen, **kw)


def _write_shard(path, records):
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    return str(path)


@pytest.fixture(autouse=True)
def _disarmed():
    faults.disarm()
    yield
    faults.disarm()


class TestClockOffsets:
    def test_reference_host_is_zero(self):
        shards = [("coord", [_rec("gauge", 1.0, name="g", value=1)])]
        assert fleet.clock_offsets(shards) == {"coord": 0.0}

    def test_one_way_bound(self):
        # w1's clock reads 0.5 s ahead of coord's (message can only age
        # in flight, so recv − sent upper-bounds the offset)
        shards = [("coord", [_rec("gauge", 1.0, name="g", value=1)]),
                  ("w1", [_clock(1.0, "coord", 100.0, 100.5)])]
        offs = fleet.clock_offsets(shards)
        assert offs["coord"] == 0.0
        assert offs["w1"] == pytest.approx(0.5)

    def test_min_over_samples_is_tightest(self):
        shards = [("coord", [_rec("gauge", 1.0, name="g", value=1)]),
                  ("w1", [_clock(1.0, "coord", 100.0, 100.9),
                          _clock(2.0, "coord", 200.0, 200.5)])]
        assert fleet.clock_offsets(shards)["w1"] == pytest.approx(0.5)

    def test_two_way_midpoint_cancels_delay(self):
        # w0 → coord bound: +2.0; coord → w0 bound: −1.5; the midpoint
        # (2.0 − (−1.5)) / 2 = 1.75 cancels the symmetric delay part
        shards = [("coord", [_clock(1.0, "w0", 200.0, 198.5)]),
                  ("w0", [_clock(1.0, "coord", 100.0, 102.0)])]
        assert fleet.clock_offsets(shards)["w0"] == pytest.approx(1.75)

    def test_bfs_propagates_through_intermediate_host(self):
        # w2 only ever exchanged samples with w1 — its offset composes
        # w1's coord-relative offset with the w2−w1 pair estimate
        shards = [("coord", [_rec("gauge", 1.0, name="g", value=1)]),
                  ("w1", [_clock(1.0, "coord", 100.0, 100.5)]),
                  ("w2", [_clock(1.0, "w1", 50.0, 50.2)])]
        offs = fleet.clock_offsets(shards)
        assert offs["w1"] == pytest.approx(0.5)
        assert offs["w2"] == pytest.approx(0.7)

    def test_unreachable_host_defaults_to_zero(self):
        shards = [("coord", [_rec("gauge", 1.0, name="g", value=1)]),
                  ("w9", [_rec("gauge", 1.0, name="g", value=1)])]
        assert fleet.clock_offsets(shards)["w9"] == 0.0

    def test_reference_override(self):
        shards = [("coord", [_rec("gauge", 1.0, name="g", value=1)]),
                  ("w1", [_clock(1.0, "coord", 100.0, 100.5)])]
        offs = fleet.clock_offsets(shards, reference="w1")
        assert offs["w1"] == 0.0
        assert offs["coord"] == pytest.approx(-0.5)


class TestMerge:
    def test_aligned_merge_is_monotone(self):
        # raw timestamps interleave the wrong way; after subtracting
        # w0's +1.0 s offset the fleet order is causal
        shards = [("coord", [_rec("gauge", 1.0, name="a", value=1),
                             _rec("gauge", 3.0, name="c", value=1)]),
                  ("w0", [_rec("gauge", 2.5, name="b", value=1)])]
        merged = fleet.merge(shards, offsets={"coord": 0.0, "w0": 1.0})
        assert [r["name"] for r in merged] == ["a", "b", "c"]
        assert [r["_host"] for r in merged] == ["coord", "w0", "coord"]
        ts = [r["ts_fleet"] for r in merged]
        assert ts == sorted(ts)
        assert merged[1]["ts_fleet"] == pytest.approx(1.5)

    def test_tie_breaks_by_host_then_file_order(self):
        shards = [("w1", [_rec("gauge", 5.0, name="x", value=1),
                          _rec("gauge", 5.0, name="y", value=1)]),
                  ("w0", [_rec("gauge", 5.0, name="z", value=1)])]
        merged = fleet.merge(shards, offsets={})
        assert [(r["_host"], r["name"]) for r in merged] == \
            [("w0", "z"), ("w1", "x"), ("w1", "y")]

    def test_records_without_numeric_ts_dropped(self):
        shards = [("w0", [{"type": "gauge", "name": "g"},
                          _rec("gauge", 1.0, name="h", value=1)])]
        merged = fleet.merge(shards, offsets={})
        assert [r["name"] for r in merged] == ["h"]

    def test_source_records_not_mutated(self):
        rec = _rec("gauge", 1.0, name="g", value=1)
        fleet.merge([("w0", [rec])], offsets={"w0": 0.5})
        assert "_host" not in rec and "ts_fleet" not in rec


class TestCriticalPath:
    def _merged(self):
        recs = [_el(10.0, "host_fail", 0, detect_s=0.7, failed_host=2),
                _el(10.1, "host_fail", 0, detect_s=0.4, failed_host=2),
                _el(10.5, "shrink", 1),
                _el(11.0, "world_up", 1),
                _el(11.2, "resume", 1, cursor=8),
                _el(12.0, "done", 1)]
        return fleet.merge([("w0", recs)], offsets={"w0": 0.0})

    def test_segments_hand_math(self):
        paths = fleet.critical_path(self._merged())
        assert len(paths) == 1
        p = paths[0]
        assert p["generation"] == 1
        # slowest surviving host's own lease-layer measurement
        assert p["detect_s"] == pytest.approx(0.7)
        assert p["shrink_s"] == pytest.approx(0.5)
        assert p["reinit_s"] == pytest.approx(0.5)
        assert p["resume_s"] == pytest.approx(0.2)
        assert p["finish_s"] == pytest.approx(0.8)
        assert p["total_s"] == pytest.approx(2.0)

    def test_missing_anchor_segments_are_none(self):
        recs = [_el(10.0, "host_fail", 0, detect_s=0.7),
                _el(11.0, "world_up", 1)]
        p = fleet.critical_path(fleet.merge([("w0", recs)], offsets={}))[0]
        assert p["resume_s"] is None
        assert p["finish_s"] is None
        assert p["total_s"] is None
        assert p["detect_s"] == pytest.approx(0.7)

    def test_no_shrink_means_no_paths(self):
        recs = [_el(1.0, "world_up", 0), _el(5.0, "done", 0)]
        assert fleet.critical_path(
            fleet.merge([("w0", recs)], offsets={})) == []


class TestReconcile:
    def _commits(self, windows):
        return fleet.merge(
            [("coord", [_el(float(i), "commit", 1, window=w, cursor=w)
                        for i, w in enumerate(windows)])], offsets={})

    def test_each_window_exactly_once_is_ok(self):
        r = fleet.reconcile(self._commits([0, 1, 2]))
        assert r["ok"] and r["windows"] == 3 and r["committed"] == 3
        assert r["duplicates"] == [] and r["gaps"] == []
        assert r["max_cursor"] == 2

    def test_duplicate_commit_flagged(self):
        r = fleet.reconcile(self._commits([0, 1, 1]))
        assert not r["ok"]
        assert r["duplicates"] == [1]

    def test_gap_flagged(self):
        r = fleet.reconcile(self._commits([0, 2]))
        assert not r["ok"]
        assert r["gaps"] == [1]

    def test_vacuously_ok_without_commits(self):
        r = fleet.reconcile([])
        assert r["ok"] and r["windows"] == 0 and r["max_cursor"] is None


class TestLoadShards:
    def test_envelope_wins_filename_falls_back(self, tmp_path):
        env = {"run_id": "r1", "host": "workerA", "pid": 1, "gen": 0}
        _write_shard(tmp_path / "obs.w0.jsonl",
                     [_rec("gauge", 1.0, name="g", value=1, fleet=env)])
        _write_shard(tmp_path / "obs.zz.jsonl",
                     [_rec("gauge", 1.0, name="g", value=1)])
        hosts = [h for h, _ in fleet.load_shards(str(tmp_path))]
        assert hosts == ["workerA", "zz"]

    def test_coordinator_sorts_first_and_empty_dropped(self, tmp_path):
        env = {"run_id": "r1", "host": "coord", "pid": 1, "gen": 0}
        _write_shard(tmp_path / "obs.w0.jsonl",
                     [_rec("gauge", 1.0, name="g", value=1)])
        _write_shard(tmp_path / "obs.zcoord.jsonl",
                     [_rec("gauge", 1.0, name="g", value=1, fleet=env)])
        _write_shard(tmp_path / "obs.empty.jsonl", [])
        hosts = [h for h, _ in fleet.load_shards(str(tmp_path))]
        assert hosts == ["coord", "w0"]

    def test_gzipped_shard_loads(self, tmp_path):
        p = tmp_path / "obs.w3.jsonl.gz"
        with gzip.open(p, "wt") as f:
            f.write(json.dumps(_rec("gauge", 1.0, name="g", value=1)) + "\n")
        shards = fleet.load_shards([str(p)])
        assert [h for h, _ in shards] == ["w3"]
        assert shards[0][1][0]["name"] == "g"

    def test_run_ids_collected(self, tmp_path):
        env = {"run_id": "elastic-ab12", "host": "w0", "pid": 1, "gen": 0}
        _write_shard(tmp_path / "obs.w0.jsonl",
                     [_rec("gauge", 1.0, name="g", value=1, fleet=env)])
        assert fleet.run_ids(fleet.load_shards(str(tmp_path))) == \
            ["elastic-ab12"]


class TestRecorderFleetEnvelope:
    def test_envelope_stamped_on_every_record(self, tmp_path,
                                              monkeypatch):
        monkeypatch.delenv("SQ_OBS_FLEET_RUN_ID", raising=False)
        path = str(tmp_path / "obs.h0.jsonl")
        rec = Recorder(path, run_id="r-42", host="h0")
        rec.record(_rec("gauge", 1.0, name="g", value=1))
        rec.fleet_generation = 2
        rec.record(_rec("gauge", 2.0, name="g", value=2))
        rec.close()
        lines = [json.loads(ln) for ln in open(path)]
        # meta + two gauges, every one carrying the envelope
        assert lines[0]["type"] == "meta"
        for ln in lines:
            assert ln["fleet"]["run_id"] == "r-42"
            assert ln["fleet"]["host"] == "h0"
            assert ln["fleet"]["pid"] == os.getpid()
            assert not schema.validate_record(ln)
        assert lines[1]["fleet"]["gen"] is None
        assert lines[2]["fleet"]["gen"] == 2

    def test_no_envelope_without_run_id(self, tmp_path, monkeypatch):
        monkeypatch.delenv("SQ_OBS_FLEET_RUN_ID", raising=False)
        path = str(tmp_path / "obs.jsonl")
        rec = Recorder(path)
        rec.record(_rec("gauge", 1.0, name="g", value=1))
        rec.close()
        lines = [json.loads(ln) for ln in open(path)]
        assert all("fleet" not in ln for ln in lines)

    def test_set_fleet_and_generation_adopt_active(self, tmp_path,
                                                   monkeypatch):
        monkeypatch.delenv("SQ_OBS_FLEET_RUN_ID", raising=False)
        path = str(tmp_path / "obs.sim.jsonl")
        obs.enable(path)
        try:
            obs_recorder.set_fleet("r-sim", host="sim")
            obs_recorder.set_generation(3)
            obs_recorder.get_recorder().record(
                _rec("gauge", 1.0, name="g", value=1))
            assert obs_recorder.flush(fsync=True) is True
        finally:
            obs.disable()
        gauge = [json.loads(ln) for ln in open(path)
                 if json.loads(ln)["type"] == "gauge"]
        assert gauge[0]["fleet"] == {"run_id": "r-sim", "host": "sim",
                                     "pid": os.getpid(), "gen": 3}

    def test_flush_without_sink_is_false(self):
        rec = Recorder()
        assert rec.flush(fsync=True) is False

    def test_flush_without_active_recorder_is_false(self):
        obs.disable()
        assert obs_recorder.flush(fsync=True) is False


def _fleet_run_dir(tmp_path):
    """Hand-built 3-process shards of one run spanning generations
    0 → 1: coordinator ledger (windows 0–3, window 2 recommitted by the
    shrunk world after w1 dies mid-window), per-worker fold progress,
    and two-way clock samples. w1's shard ends mid-window — SIGKILL."""
    env = {"run_id": "r-e2e", "pid": 1, "gen": 0}

    def fl(host, gen=0):
        return dict(env, host=host, gen=gen)

    # true offsets: w0 clock = coord + 10.3, w1 clock = coord + 19.5;
    # each direction's sample carries 0.1 s of in-flight delay, which
    # the two-way midpoints cancel exactly
    coord = [
        _rec("meta", 0.0, pid=1, schema=V, fleet=fl("coord")),
        _clock(0.2, "w0", 10.4, 0.2, via="manifest", fleet=fl("coord")),
        _clock(0.2, "w1", 19.6, 0.2, via="manifest", fleet=fl("coord")),
        _el(0.5, "world_up", 0, n_hosts=2, fleet=fl("coord")),
        _el(1.0, "commit", 0, window=0, cursor=0, fleet=fl("coord")),
        _el(2.0, "commit", 0, window=1, cursor=1, fleet=fl("coord")),
        _el(3.0, "host_fail", 0, detect_s=0.6, failed_host=1,
            fleet=fl("coord")),
        _el(3.4, "shrink", 1, fleet=fl("coord")),
        _el(4.0, "world_up", 1, n_hosts=1, fleet=fl("coord", 1)),
        _el(4.2, "resume", 1, cursor=1, fleet=fl("coord", 1)),
        _el(5.0, "commit", 1, window=2, cursor=2, fleet=fl("coord", 1)),
        _el(6.0, "commit", 1, window=3, cursor=3, fleet=fl("coord", 1)),
        _el(6.5, "done", 1, fleet=fl("coord", 1)),
    ]
    w0 = [
        _rec("meta", 10.3, pid=2, schema=V, fleet=fl("w0")),
        _clock(10.5, "coord", 0.1, 10.5, via="hb", fleet=fl("w0")),
        _el(10.9, "window", 0, window=0, fleet=fl("w0")),
        _el(11.9, "window", 0, window=1, fleet=fl("w0")),
        _el(14.9, "window", 1, window=2, fleet=fl("w0", 1)),
        _el(15.9, "window", 1, window=3, fleet=fl("w0", 1)),
    ]
    w1 = [
        _rec("meta", 19.6, pid=3, schema=V, fleet=fl("w1")),
        _clock(19.7, "coord", 0.1, 19.7, via="hb", fleet=fl("w1")),
        _el(20.2, "window", 0, window=0, fleet=fl("w1")),
        # killed mid-window 2: progress recorded, commit never issued
        _el(22.1, "window", 0, window=2, fleet=fl("w1")),
    ]
    run = tmp_path / "run"
    run.mkdir()
    _write_shard(run / "obs.coord.jsonl", coord)
    _write_shard(run / "obs.w0.jsonl", w0)
    _write_shard(run / "obs.w1.jsonl", w1)
    return run


class TestFleetEndToEnd:
    def test_summarize_multi_host_two_generations(self, tmp_path):
        run = _fleet_run_dir(tmp_path)
        s = fleet.summarize(str(run))
        assert s["run_ids"] == ["r-e2e"]
        assert sorted(s["hosts"]) == ["coord", "w0", "w1"]
        assert s["generations"] == [0, 1]
        # clock alignment: w0 ≈ +10.3 s, w1 ≈ +19.5 s vs coord
        offs = s["clock_offsets_s"]
        assert offs["coord"] == 0.0
        assert offs["w0"] == pytest.approx(10.3, abs=1e-6)
        assert offs["w1"] == pytest.approx(19.5, abs=1e-6)
        # ledger: 4 windows, the voided one recommitted exactly once
        recon = s["reconciliation"]
        assert recon["ok"] and recon["windows"] == 4
        assert recon["duplicates"] == [] and recon["gaps"] == []
        # gen-1 shrink critical path fully decomposed
        cp = [p for p in s["critical_path"] if p["generation"] == 1]
        assert len(cp) == 1
        assert cp[0]["detect_s"] == pytest.approx(0.6)
        assert cp[0]["total_s"] == pytest.approx(3.5)
        # the dead worker's pre-kill progress is in the rollups
        assert s["rollups"]["w1"]["by_type"]["elastic"] == 2
        txt = fleet.render(s)
        assert "r-e2e" in txt and "w1" in txt

    def test_merged_artifact_monotone_and_schema_valid(self, tmp_path):
        run = _fleet_run_dir(tmp_path)
        shards = fleet.load_shards(str(run))
        out = str(tmp_path / "merged.jsonl")
        fleet.write_merged(shards, out)
        summary = schema.validate_jsonl(out)
        assert summary["errors"] == []
        merged = [json.loads(ln) for ln in open(out)]
        n_records = sum(len(recs) for _, recs in shards)
        assert len(merged) == n_records
        ts = [r["ts_fleet"] for r in merged]
        assert ts == sorted(ts)
        assert {r["_host"] for r in merged} == {"coord", "w0", "w1"}

    def test_cli_json_trace_and_exit_codes(self, tmp_path, capsys):
        run = _fleet_run_dir(tmp_path)
        trace = tmp_path / "trace.json"
        merged = tmp_path / "m.jsonl"
        rc = fleet.main([str(run), "--json", "-o", str(trace),
                         "--merged", str(merged)])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["run_ids"] == ["r-e2e"]
        assert trace.exists() and merged.exists()
        tr = json.loads(trace.read_text())
        events = tr["traceEvents"] if isinstance(tr, dict) else tr
        assert events

    def test_cli_exit_1_on_broken_ledger(self, tmp_path):
        run = tmp_path / "run"
        run.mkdir()
        _write_shard(run / "obs.coord.jsonl",
                     [_el(1.0, "commit", 0, window=0, cursor=0),
                      _el(2.0, "commit", 0, window=0, cursor=0)])
        assert fleet.main([str(run)]) == 1

    def test_cli_exit_2_without_shards(self, tmp_path):
        empty = tmp_path / "empty"
        empty.mkdir()
        assert fleet.main([str(empty)]) == 2

    def test_sim_shard_reconciles_through_fleet(self, tmp_path,
                                                monkeypatch):
        # the in-process elastic sim, shrunk mid-epoch, must produce a
        # shard whose ledger reconciles and whose gen-1 path decomposes
        monkeypatch.delenv("SQ_OBS_FLEET_RUN_ID", raising=False)
        path = str(tmp_path / "obs.sim.jsonl")
        obs.enable(path)
        try:
            obs_recorder.set_fleet("r-sim", host="sim")
            rng = np.random.default_rng(5)
            x = np.asarray(rng.normal(size=(230, 7)), np.float64)
            src = ArraySource(x, shard_rows=16)  # 15 shards
            faults.arm("host_fail:window=1,host=0,times=1")
            elastic.elastic_fit_local(src, 3, n_hosts=3, seed=1,
                                      epochs=1, window=4)
        finally:
            faults.disarm()
            obs.disable()
        s = fleet.summarize([(h, r) for h, r in
                             fleet.load_shards(str(path))])
        assert s["run_ids"] == ["r-sim"]
        assert len(s["generations"]) >= 2
        recon = s["reconciliation"]
        assert recon["ok"]
        assert recon["windows"] == 4  # ceil(15 / 4) windows, 1 epoch


class TestReportFleetSection:
    def test_summary_counts_envelope_and_ledger(self, tmp_path):
        run = _fleet_run_dir(tmp_path)
        records = [r for _, recs in fleet.load_shards(str(run))
                   for r in recs]
        s = report.summarize(records)
        fl = s["fleet"]
        assert fl["run_ids"] == ["r-e2e"]
        assert fl["hosts"] == {"coord": 13, "w0": 6, "w1": 4}
        assert fl["generations"] == [0, 1]
        assert fl["commits"] == 4
        assert fl["windows"] == 6
        assert fl["clock_samples"] == 4
        txt = report.render(s)
        assert "fleet (cross-process correlation)" in txt
        assert "r-e2e" in txt

    def test_section_silent_without_fleet_records(self):
        s = report.summarize([_rec("gauge", 1.0, name="g", value=1)])
        assert s["fleet"]["run_ids"] == []
        assert "fleet (cross-process correlation)" not in report.render(s)
