"""Mini-batch q-means tests (reference MiniBatchKMeans intent,
``_dmeans.py:1587``; minibatch-vs-batch consistency pattern from
``cluster/tests/test_k_means.py:176``)."""

import numpy as np
import pytest

from sq_learn_tpu.datasets import make_blobs
from sq_learn_tpu.metrics import adjusted_rand_score
from sq_learn_tpu.models import KMeans, MiniBatchKMeans, MiniBatchQKMeans


@pytest.fixture(scope="module")
def blobs():
    return make_blobs(n_samples=600, centers=4, n_features=8,
                      cluster_std=0.6, random_state=3)


def test_minibatch_matches_batch_on_blobs(blobs):
    X, y = blobs
    mb = MiniBatchKMeans(n_clusters=4, batch_size=128, max_iter=30,
                         n_init=3, random_state=0).fit(X)
    assert adjusted_rand_score(y, mb.labels_) > 0.95
    full = KMeans(n_clusters=4, n_init=3, random_state=0).fit(X)
    # within 10% of full-batch inertia (Sculley-style guarantee in practice)
    assert mb.inertia_ <= full.inertia_ * 1.10


def test_minibatch_quantum_delta_mode(blobs):
    X, y = blobs
    mb = MiniBatchQKMeans(n_clusters=4, batch_size=128, max_iter=20,
                          n_init=2, delta=0.05,
                          random_state=0).fit(X)
    assert adjusted_rand_score(y, mb.labels_) > 0.8
    assert mb.predict(X[:10]).shape == (10,)


def test_partial_fit_incremental(blobs):
    X, y = blobs
    mb = MiniBatchQKMeans(n_clusters=4, random_state=0)
    rng = np.random.default_rng(0)
    for _ in range(30):
        idx = rng.choice(X.shape[0], 128, replace=False)
        mb.partial_fit(X[idx])
    assert mb.n_steps_ == 30
    labels = mb.predict(X)
    assert adjusted_rand_score(y, labels) > 0.9


def test_partial_fit_weights_and_counts(blobs):
    X, _ = blobs
    mb = MiniBatchQKMeans(n_clusters=4, random_state=1)
    mb.partial_fit(X[:200], sample_weight=np.ones(200))
    total = float(mb.counts_.sum())
    assert total == pytest.approx(200.0)
    mb.partial_fit(X[200:400])
    assert float(mb.counts_.sum()) == pytest.approx(400.0)


def test_minibatch_transform_score(blobs):
    X, _ = blobs
    mb = MiniBatchKMeans(n_clusters=4, random_state=0, max_iter=10,
                         n_init=1).fit(X)
    T = mb.transform(X[:5])
    assert T.shape == (5, 4)
    assert mb.score(X) == pytest.approx(-mb.inertia_, rel=1e-5)


def test_batch_padding_zero_weight():
    # n not divisible by batch_size: padded duplicate rows must not shift
    # centers (their weight is zeroed)
    X, y = make_blobs(n_samples=130, centers=3, n_features=4,
                      cluster_std=0.3, random_state=7)
    mb = MiniBatchKMeans(n_clusters=3, batch_size=64, max_iter=20,
                         n_init=2, random_state=0).fit(X)
    assert adjusted_rand_score(y, mb.labels_) > 0.95
