"""Mini-batch q-means tests (reference MiniBatchKMeans intent,
``_dmeans.py:1587``; minibatch-vs-batch consistency pattern from
``cluster/tests/test_k_means.py:176``)."""

import warnings

import numpy as np
import pytest

from sq_learn_tpu.datasets import make_blobs
from sq_learn_tpu.metrics import adjusted_rand_score
from sq_learn_tpu.models import KMeans, MiniBatchKMeans, MiniBatchQKMeans


@pytest.fixture(scope="module")
def blobs():
    return make_blobs(n_samples=600, centers=4, n_features=8,
                      cluster_std=0.6, random_state=3)


def test_minibatch_matches_batch_on_blobs(blobs):
    X, y = blobs
    mb = MiniBatchKMeans(n_clusters=4, batch_size=128, max_iter=30,
                         n_init=3, random_state=0).fit(X)
    assert adjusted_rand_score(y, mb.labels_) > 0.95
    full = KMeans(n_clusters=4, n_init=3, random_state=0).fit(X)
    # within 10% of full-batch inertia (Sculley-style guarantee in practice)
    assert mb.inertia_ <= full.inertia_ * 1.10


def test_minibatch_quantum_delta_mode(blobs):
    X, y = blobs
    mb = MiniBatchQKMeans(n_clusters=4, batch_size=128, max_iter=20,
                          n_init=2, delta=0.05,
                          random_state=0).fit(X)
    assert adjusted_rand_score(y, mb.labels_) > 0.8
    assert mb.predict(X[:10]).shape == (10,)


def test_partial_fit_incremental(blobs):
    X, y = blobs
    mb = MiniBatchQKMeans(n_clusters=4, random_state=0)
    rng = np.random.default_rng(0)
    for _ in range(30):
        idx = rng.choice(X.shape[0], 128, replace=False)
        mb.partial_fit(X[idx])
    assert mb.n_steps_ == 30
    labels = mb.predict(X)
    assert adjusted_rand_score(y, labels) > 0.9


def test_partial_fit_weights_and_counts(blobs):
    X, _ = blobs
    mb = MiniBatchQKMeans(n_clusters=4, random_state=1)
    mb.partial_fit(X[:200], sample_weight=np.ones(200))
    total = float(mb.counts_.sum())
    assert total == pytest.approx(200.0)
    mb.partial_fit(X[200:400])
    assert float(mb.counts_.sum()) == pytest.approx(400.0)


def test_minibatch_transform_score(blobs):
    X, _ = blobs
    mb = MiniBatchKMeans(n_clusters=4, random_state=0, max_iter=10,
                         n_init=1).fit(X)
    T = mb.transform(X[:5])
    assert T.shape == (5, 4)
    assert mb.score(X) == pytest.approx(-mb.inertia_, rel=1e-5)


def test_batch_padding_zero_weight():
    # n not divisible by batch_size: padded duplicate rows must not shift
    # centers (their weight is zeroed)
    X, y = make_blobs(n_samples=130, centers=3, n_features=4,
                      cluster_std=0.3, random_state=7)
    mb = MiniBatchKMeans(n_clusters=3, batch_size=64, max_iter=20,
                         n_init=2, random_state=0).fit(X)
    assert adjusted_rand_score(y, mb.labels_) > 0.95


class TestReassignment:
    def test_low_count_center_teleports(self):
        """_random_reassign (reference _dmeans.py:1590-1618): a center with
        near-zero accumulated weight jumps to a batch row and its count
        resets to the smallest surviving count."""
        import jax
        import jax.numpy as jnp

        from sq_learn_tpu.models.minibatch import _random_reassign

        rng = np.random.RandomState(0)
        Xb = jnp.asarray(rng.randn(64, 3).astype(np.float32))
        wb = jnp.ones(64, jnp.float32)
        centers = jnp.asarray(np.vstack([np.zeros(3), np.ones(3) * 100,
                                         np.ones(3) * 5, -np.ones(3)]))
        counts = jnp.asarray([200.0, 0.5, 150.0, 120.0])
        # min count 0.5 → floor 0 → cadence modulo 10 → step_idx=9 fires
        c2, n2 = _random_reassign(jax.random.PRNGKey(0), Xb, wb, centers,
                                  counts, jnp.asarray(9), 0.01)
        moved = np.asarray(c2[1])
        assert not np.allclose(moved, np.asarray(centers[1]))
        # the new center is an actual batch row
        assert np.min(np.abs(np.asarray(Xb) - moved).sum(axis=1)) < 1e-5
        assert float(n2[1]) == pytest.approx(120.0)  # min surviving count
        # non-low centers untouched
        np.testing.assert_allclose(np.asarray(c2[0]), np.asarray(centers[0]))
        np.testing.assert_allclose(np.asarray(n2)[[0, 2, 3]],
                                   [200.0, 150.0, 120.0])

    def test_not_due_is_noop(self):
        import jax
        import jax.numpy as jnp

        from sq_learn_tpu.models.minibatch import _random_reassign

        Xb = jnp.asarray(np.random.RandomState(1).randn(32, 3).astype(
            np.float32))
        wb = jnp.ones(32, jnp.float32)
        centers = jnp.asarray(np.random.RandomState(2).randn(4, 3).astype(
            np.float32))
        counts = jnp.asarray([200.0, 0.5, 150.0, 120.0])
        c2, n2 = _random_reassign(jax.random.PRNGKey(0), Xb, wb, centers,
                                  counts, jnp.asarray(3), 0.01)
        np.testing.assert_allclose(np.asarray(c2), np.asarray(centers))
        np.testing.assert_allclose(np.asarray(n2), np.asarray(counts))

    def test_dead_center_recovers_in_fit(self):
        """A center initialized on a far outlier (never wins a point after
        the blobs dominate) gets reassigned during fit instead of staying
        dead, so all clusters end up used."""
        X, y = make_blobs(n_samples=600, centers=3, n_features=4,
                          cluster_std=0.4, random_state=11)
        X = np.vstack([X, np.full((1, 4), 1e3)]).astype(np.float32)
        w = np.ones(601, np.float32)
        w[-1] = 0.0  # the outlier row itself carries no weight
        init = np.vstack([X[:3], X[-1:]]).astype(np.float32)  # 4th center dead
        mb = MiniBatchQKMeans(n_clusters=4, init=init, batch_size=128,
                              max_iter=30, n_init=1, random_state=0,
                              reassignment_ratio=0.05)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            mb.fit(X, sample_weight=w)
        # the dead center must have left the outlier
        assert np.abs(mb.cluster_centers_).max() < 100.0


def test_compute_labels_and_init_size():
    import numpy as np
    import warnings
    from sq_learn_tpu.datasets import make_blobs
    from sq_learn_tpu.models import MiniBatchKMeans, MiniBatchQKMeans

    X, y = make_blobs(n_samples=400, centers=4, n_features=6, random_state=1)
    X = X.astype(np.float32)
    # compute_labels=False: centers fitted, labels_/inertia_ left unset
    # (upstream sklearn contract)
    mb = MiniBatchKMeans(n_clusters=4, compute_labels=False, max_iter=10,
                         random_state=0).fit(X)
    assert mb.cluster_centers_.shape == (4, 6)
    assert not hasattr(mb, "labels_") and not hasattr(mb, "inertia_")
    assert mb.predict(X).shape == (400,)  # inference still works
    # explicit init_size: candidate scoring runs on the subsample and the
    # fit still recovers the blob structure; init_size below n_clusters
    # warns and falls back to 3·n_clusters (upstream semantics)
    import pytest
    from sklearn.metrics import adjusted_rand_score
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        q = MiniBatchQKMeans(n_clusters=4, n_init=3, init_size=50,
                             max_iter=20, random_state=0).fit(X)
    with pytest.warns(RuntimeWarning, match="init_size"):
        with warnings.catch_warnings():
            warnings.filterwarnings("ignore", message="Attention!")
            tiny = MiniBatchQKMeans(n_clusters=4, n_init=2, init_size=1,
                                    max_iter=10, random_state=0).fit(X)
    assert adjusted_rand_score(y, q.labels_) > 0.9
    assert np.isfinite(tiny.inertia_)
    # partial_fit honors compute_labels the same way fit does
    pf = MiniBatchQKMeans(n_clusters=4, compute_labels=False,
                          random_state=0)
    pf.partial_fit(X[:100])
    assert not hasattr(pf, "labels_") and not hasattr(pf, "inertia_")
    pf2 = MiniBatchQKMeans(n_clusters=4, random_state=0)
    pf2.partial_fit(X[:100])
    assert pf2.labels_.shape == (100,) and np.isfinite(pf2.inertia_)


def test_n_init_auto():
    import numpy as np
    from sq_learn_tpu.datasets import make_blobs
    from sq_learn_tpu.models import MiniBatchKMeans

    X, _ = make_blobs(n_samples=300, centers=3, n_features=4, random_state=0)
    est = MiniBatchKMeans(n_clusters=3, n_init="auto", max_iter=5,
                          random_state=0).fit(X.astype(np.float32))
    assert np.isfinite(est.inertia_)
    # sklearn semantics: 'auto' is 1 for the default k-means++ init
    r = MiniBatchKMeans(n_clusters=3, n_init="auto", init="random",
                        max_iter=5, random_state=0).fit(X.astype(np.float32))
    assert np.isfinite(r.inertia_)
    import pytest as _pytest
    with _pytest.raises(ValueError, match="n_init"):
        MiniBatchKMeans(n_clusters=3, n_init="Auto").fit(
            X.astype(np.float32))


def test_partial_fit_feature_mismatch_rejected():
    import numpy as np
    import pytest as _pytest
    from sq_learn_tpu.models import MiniBatchQKMeans

    est = MiniBatchQKMeans(n_clusters=2, random_state=0)
    est.partial_fit(np.ones((8, 5), np.float32) * np.arange(8)[:, None])
    with _pytest.raises(ValueError, match="expecting 5 features"):
        est.partial_fit(np.ones((8, 3), np.float32))
    # state untouched by the rejected call
    assert est.n_features_in_ == 5
    assert est.cluster_centers_.shape == (2, 5)


def test_host_and_device_paths_agree(monkeypatch):
    """The CPU host fast path and the scanned XLA path are semantics
    twins: same init-selection shape, Sculley updates, EWA stopping, and
    reassignment schedule — different RNG streams, so compare clustering
    quality, not bits."""
    from sq_learn_tpu.models.qkmeans import QKMeans as _QK

    X, y = make_blobs(n_samples=600, centers=4, n_features=6,
                      cluster_std=0.7, random_state=3)
    X = X.astype(np.float32)
    host = MiniBatchQKMeans(n_clusters=4, random_state=0, batch_size=128,
                            n_init=3).fit(X)
    monkeypatch.setattr(_QK, "_on_cpu_backend", staticmethod(lambda: False))
    dev = MiniBatchQKMeans(n_clusters=4, random_state=0, batch_size=128,
                           n_init=3).fit(X)
    assert np.isfinite(host.inertia_) and np.isfinite(dev.inertia_)
    # both converge to the same well-separated clustering
    assert adjusted_rand_score(host.labels_, y) > 0.95
    assert adjusted_rand_score(dev.labels_, y) > 0.95
    assert host.inertia_ == pytest.approx(dev.inertia_, rel=0.1)
    assert host.cluster_centers_.shape == dev.cluster_centers_.shape
    # host path reports the same bookkeeping surface
    assert host.n_steps_ >= host.n_iter_ >= 1


def test_host_path_delta_mode_and_reassignment():
    """δ-means label noise and low-count reassignment run inside the host
    engine: a fit with a tiny reassignment_ratio and δ>0 must stay finite
    and keep every cluster populated on well-separated data."""
    X, y = make_blobs(n_samples=400, centers=4, n_features=5,
                      cluster_std=0.5, random_state=1)
    X = X.astype(np.float32)
    est = MiniBatchQKMeans(n_clusters=4, random_state=2, delta=0.5,
                           batch_size=100, reassignment_ratio=0.05).fit(X)
    assert np.isfinite(est.inertia_)
    assert len(np.unique(est.labels_)) == 4
    assert adjusted_rand_score(est.labels_, y) > 0.9


def test_labels_agree_with_predict_in_delta_mode():
    """labels_ is an inference artifact: deterministic argmin under the
    final centers, identical to predict(X) — the δ-window noise perturbs
    TRAINING assignments only (device `_full_assign` contract)."""
    X, _ = make_blobs(n_samples=300, centers=3, n_features=4,
                      cluster_std=0.6, random_state=5)
    X = X.astype(np.float32)
    est = MiniBatchQKMeans(n_clusters=3, random_state=0, delta=0.5,
                           batch_size=64).fit(X)
    np.testing.assert_array_equal(est.labels_, est.predict(X))
