"""bench/_gate.py — the suite acceptance gate's counting rules.

The gate is the enforcement point of BASELINE.md's "within 2x" bar
(vs_baseline >= 0.5 on the measurement of record), so its edge cases —
null baselines, missing configs, the measured/derived split added in
round 5 — are pinned here rather than living untested inside
run_suite.sh.
"""

import io
import json

import pytest

from bench._gate import check, main


def _record(tmp_path, lines):
    p = tmp_path / "rec.txt"
    p.write_text("# suite run\n" + "\n".join(
        json.dumps(rec) if isinstance(rec, dict) else rec
        for rec in lines) + "\n")
    return str(p)


def _line(metric, vs_baseline, **extra):
    rec = {"metric": metric, "value": 1.0, "unit": "s",
           "vs_baseline": vs_baseline}
    rec.update(extra)
    return rec


class TestGateCheck:
    def test_green_record(self, tmp_path):
        path = _record(tmp_path, [
            _line("a", 2.0), _line("b", 0.9), _line("c", 1.2),
            _line("d", 0.6), _line("e", 0.51),
            _line("ipe", 5.5e4, baseline_kind="derived")])
        fails, measured, derived = check(path, 5, 1, out=io.StringIO())
        assert fails == [] and (measured, derived) == (5, 1)
        main([path, "5", "1"])  # exits 0

    def test_below_bar_fails(self, tmp_path):
        path = _record(tmp_path, [_line("slow", 0.49)])
        fails, _, _ = check(path, 1, 0, out=io.StringIO())
        assert fails == ["slow"]
        with pytest.raises(SystemExit, match="slow"):
            main([path, "1", "0"])

    def test_null_baseline_is_a_miss_not_a_pass(self, tmp_path):
        path = _record(tmp_path, [_line("unmeasured", None)])
        fails, _, _ = check(path, 1, 0, out=io.StringIO())
        assert fails == ["unmeasured"]

    def test_missing_config_fails_even_if_all_present_pass(self, tmp_path):
        # double failure = only rc markers in the record, no JSON line
        path = _record(tmp_path, [_line("a", 2.0), "# rc=124"])
        with pytest.raises(SystemExit, match="measured=1/2"):
            main([path, "2", "0"])

    def test_derived_never_counts_toward_measured(self, tmp_path):
        # a derived line must not paper over a missing measured config...
        path = _record(tmp_path, [
            _line("a", 2.0),
            _line("ipe", 5.5e4, baseline_kind="derived")])
        with pytest.raises(SystemExit, match="measured=1/2"):
            main([path, "2", "0"])
        # ...and a missing derived line fails too
        with pytest.raises(SystemExit, match="derived=1/2"):
            main([path, "1", "2"])

    def test_derived_lines_share_the_bar(self, tmp_path):
        # >= 0.5 means "not slower than the reference's serial
        # architecture" — a derived ratio below it is a real failure
        path = _record(tmp_path, [
            _line("ipe", 0.3, baseline_kind="derived")])
        fails, measured, derived = check(path, 0, 1, out=io.StringIO())
        assert fails == ["ipe"] and (measured, derived) == (0, 1)

    def test_non_json_and_malformed_lines_ignored(self, tmp_path):
        path = _record(tmp_path, [
            "# ACCEPT pass: stale", "{not json", '{"metric": "no_vb"}',
            _line("a", 1.0)])
        fails, measured, derived = check(path, 1, 0, out=io.StringIO())
        assert fails == [] and (measured, derived) == (1, 0)


class TestGateMachineReadableOutput:
    """The {"gate": ..., "verdict": ...} JSON line per criterion — the
    format the perf-regression analyzer (and CI annotations) consume,
    next to the historical # ACCEPT comments."""

    def _json_lines(self, text):
        return [json.loads(l) for l in text.splitlines()
                if l.startswith("{")]

    def test_one_json_verdict_per_metric(self, tmp_path):
        path = _record(tmp_path, [
            _line("fast", 2.0), _line("slow", 0.3),
            _line("ipe", 5.5e4, baseline_kind="derived")])
        out = io.StringIO()
        check(path, 2, 1, out=out)
        lines = self._json_lines(out.getvalue())
        assert len(lines) == 3
        by_metric = {l["metric"]: l for l in lines}
        assert all(l["gate"] == "vs_baseline" for l in lines)
        assert by_metric["fast"]["verdict"] == "pass"
        assert by_metric["slow"]["verdict"] == "fail"
        assert by_metric["slow"]["threshold"] == 0.5
        assert by_metric["ipe"]["kind"] == "derived"

    def test_main_emits_counts_verdict_line(self, tmp_path, capsys):
        path = _record(tmp_path, [_line("a", 2.0)])
        main([path, "1", "0"])
        lines = self._json_lines(capsys.readouterr().out)
        counts = [l for l in lines if l["gate"] == "counts"]
        assert len(counts) == 1
        assert counts[0]["verdict"] == "pass"
        assert counts[0]["measured"] == 1 and counts[0]["derived"] == 0

    def test_counts_line_fails_on_missing_config(self, tmp_path, capsys):
        path = _record(tmp_path, [_line("a", 2.0)])
        with pytest.raises(SystemExit):
            main([path, "2", "0"])
        lines = self._json_lines(capsys.readouterr().out)
        counts = [l for l in lines if l["gate"] == "counts"][0]
        assert counts["verdict"] == "fail"
        assert counts["expected_measured"] == 2
