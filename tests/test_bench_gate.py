"""bench/_gate.py — the suite acceptance gate's counting rules.

The gate is the enforcement point of BASELINE.md's "within 2x" bar
(vs_baseline >= 0.5 on the measurement of record), so its edge cases —
null baselines, missing configs, the measured/derived split added in
round 5 — are pinned here rather than living untested inside
run_suite.sh.
"""

import io
import json

import pytest

from bench._gate import check, main


def _record(tmp_path, lines):
    p = tmp_path / "rec.txt"
    p.write_text("# suite run\n" + "\n".join(
        json.dumps(rec) if isinstance(rec, dict) else rec
        for rec in lines) + "\n")
    return str(p)


def _line(metric, vs_baseline, **extra):
    rec = {"metric": metric, "value": 1.0, "unit": "s",
           "vs_baseline": vs_baseline}
    rec.update(extra)
    return rec


class TestGateCheck:
    def test_green_record(self, tmp_path):
        path = _record(tmp_path, [
            _line("a", 2.0), _line("b", 0.9), _line("c", 1.2),
            _line("d", 0.6), _line("e", 0.51),
            _line("ipe", 5.5e4, baseline_kind="derived")])
        fails, measured, derived = check(path, 5, 1, out=io.StringIO())
        assert fails == [] and (measured, derived) == (5, 1)
        main([path, "5", "1"])  # exits 0

    def test_below_bar_fails(self, tmp_path):
        path = _record(tmp_path, [_line("slow", 0.49)])
        fails, _, _ = check(path, 1, 0, out=io.StringIO())
        assert fails == ["slow"]
        with pytest.raises(SystemExit, match="slow"):
            main([path, "1", "0"])

    def test_null_baseline_is_a_miss_not_a_pass(self, tmp_path):
        path = _record(tmp_path, [_line("unmeasured", None)])
        fails, _, _ = check(path, 1, 0, out=io.StringIO())
        assert fails == ["unmeasured"]

    def test_missing_config_fails_even_if_all_present_pass(self, tmp_path):
        # double failure = only rc markers in the record, no JSON line
        path = _record(tmp_path, [_line("a", 2.0), "# rc=124"])
        with pytest.raises(SystemExit, match="measured=1/2"):
            main([path, "2", "0"])

    def test_derived_never_counts_toward_measured(self, tmp_path):
        # a derived line must not paper over a missing measured config...
        path = _record(tmp_path, [
            _line("a", 2.0),
            _line("ipe", 5.5e4, baseline_kind="derived")])
        with pytest.raises(SystemExit, match="measured=1/2"):
            main([path, "2", "0"])
        # ...and a missing derived line fails too
        with pytest.raises(SystemExit, match="derived=1/2"):
            main([path, "1", "2"])

    def test_derived_lines_share_the_bar(self, tmp_path):
        # >= 0.5 means "not slower than the reference's serial
        # architecture" — a derived ratio below it is a real failure
        path = _record(tmp_path, [
            _line("ipe", 0.3, baseline_kind="derived")])
        fails, measured, derived = check(path, 0, 1, out=io.StringIO())
        assert fails == ["ipe"] and (measured, derived) == (0, 1)

    def test_non_json_and_malformed_lines_ignored(self, tmp_path):
        path = _record(tmp_path, [
            "# ACCEPT pass: stale", "{not json", '{"metric": "no_vb"}',
            _line("a", 1.0)])
        fails, measured, derived = check(path, 1, 0, out=io.StringIO())
        assert fails == [] and (measured, derived) == (1, 0)
