"""Reference-namespace facade tests: a sq-learn user's import paths resolve
to the TPU-native implementations (SURVEY §0 surface)."""

import numpy as np
import jax
import pytest


def test_cluster_namespace():
    from sq_learn_tpu.cluster import KMeans, MiniBatchKMeans, qMeans_
    from sq_learn_tpu.models import QKMeans

    assert qMeans_ is QKMeans
    assert KMeans is not None and MiniBatchKMeans is not None


def test_decomposition_namespace():
    from sq_learn_tpu.decomposition import PCA, TruncatedSVD, qPCA
    from sq_learn_tpu.models import QPCA

    assert qPCA is QPCA
    assert PCA is not None and TruncatedSVD is not None


def test_svm_and_neighbors_namespaces():
    from sq_learn_tpu.neighbors import KNeighborsClassifier
    from sq_learn_tpu.svm import QLSSVC

    assert QLSSVC is not None and KNeighborsClassifier is not None


def test_quantum_utility_namespace_smoke(key=jax.random.PRNGKey(0)):
    from sq_learn_tpu import QuantumUtility as QU

    # the reference names resolve and run
    v = QU.create_rand_vec(key, 2, 8)
    assert v.shape == (2, 8)
    est = QU.make_gaussian_est(key, v[0] / np.linalg.norm(v[0]), 0.1)
    assert est.shape == (8,)
    a = QU.amplitude_estimation(key, 0.3, epsilon=0.05)
    assert abs(float(a) - 0.3) < 0.1
    e = QU.introduce_error(key, 1.0, 0.01)
    assert abs(float(e) - 1.0) <= 0.01 + 1e-6
    norm_name, best = QU.best_mu(np.eye(4, dtype=np.float32), 0.0, step=0.5)
    assert best > 0


def test_mnist_trial_style_pipeline_with_compat_imports():
    """The reference's MnistTrial pattern, written with reference-style
    imports, runs unmodified (small data)."""
    import warnings

    from sq_learn_tpu.datasets import make_blobs
    from sq_learn_tpu.decomposition import qPCA
    from sq_learn_tpu.model_selection import StratifiedKFold, cross_validate
    from sq_learn_tpu.neighbors import KNeighborsClassifier

    X, y = make_blobs(n_samples=200, centers=3, n_features=16,
                      cluster_std=1.0, random_state=0)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        pca = qPCA(n_components=4, random_state=0)
        pca.fit(X, estimate_all=True, theta_major=1e-9, eps=0.1, delta=0.1,
                true_tomography=False)
        Xt = pca.transform(X, classic_transform=False,
                           use_classical_components=False)
    res = cross_validate(KNeighborsClassifier(n_neighbors=5), Xt, y,
                         cv=StratifiedKFold(n_splits=3))
    assert np.mean(res["test_score"]) > 0.9
