"""Reference-namespace facade tests: a sq-learn user's import paths resolve
to the TPU-native implementations (SURVEY §0 surface)."""

import numpy as np
import jax
import pytest


def test_cluster_namespace():
    from sq_learn_tpu.cluster import KMeans, MiniBatchKMeans, qMeans_
    from sq_learn_tpu.models import QKMeans

    assert qMeans_ is QKMeans
    assert KMeans is not None and MiniBatchKMeans is not None


def test_decomposition_namespace():
    from sq_learn_tpu.decomposition import PCA, TruncatedSVD, qPCA
    from sq_learn_tpu.models import QPCA

    assert qPCA is QPCA
    assert PCA is not None and TruncatedSVD is not None


def test_svm_and_neighbors_namespaces():
    from sq_learn_tpu.neighbors import KNeighborsClassifier
    from sq_learn_tpu.svm import QLSSVC

    assert QLSSVC is not None and KNeighborsClassifier is not None


def test_quantum_utility_namespace_smoke(key=jax.random.PRNGKey(0)):
    from sq_learn_tpu import QuantumUtility as QU

    # the reference names resolve and run
    v = QU.create_rand_vec(key, 2, 8)
    assert v.shape == (2, 8)
    est = QU.make_gaussian_est(key, v[0] / np.linalg.norm(v[0]), 0.1)
    assert est.shape == (8,)
    a = QU.amplitude_estimation(key, 0.3, epsilon=0.05)
    assert abs(float(a) - 0.3) < 0.1
    e = QU.introduce_error(key, 1.0, 0.01)
    assert abs(float(e) - 1.0) <= 0.01 + 1e-6
    norm_name, best = QU.best_mu(np.eye(4, dtype=np.float32), 0.0, step=0.5)
    assert best > 0


def test_mnist_trial_style_pipeline_with_compat_imports():
    """The reference's MnistTrial pattern, written with reference-style
    imports, runs unmodified (small data)."""
    import warnings

    from sq_learn_tpu.datasets import make_blobs
    from sq_learn_tpu.decomposition import qPCA
    from sq_learn_tpu.model_selection import StratifiedKFold, cross_validate
    from sq_learn_tpu.neighbors import KNeighborsClassifier

    X, y = make_blobs(n_samples=200, centers=3, n_features=16,
                      cluster_std=1.0, random_state=0)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        pca = qPCA(n_components=4, random_state=0)
        pca.fit(X, estimate_all=True, theta_major=1e-9, eps=0.1, delta=0.1,
                true_tomography=False)
        Xt = pca.transform(X, classic_transform=False,
                           use_classical_components=False)
    res = cross_validate(KNeighborsClassifier(n_neighbors=5), Xt, y,
                         cv=StratifiedKFold(n_splits=3))
    assert np.mean(res["test_score"]) > 0.9


def test_reference_helper_shims():
    """The small Utility.py helpers nothing internal consumes are still
    importable drop-ins (reference ``Utility.py:404-441``,
    ``_dmeans.py:2252``)."""
    import jax
    from sq_learn_tpu import QuantumUtility as QU
    from sq_learn_tpu.cluster import select_labels
    from sq_learn_tpu.ops.quantum import QuantumState

    # check_measure: strictly increasing schedule fixup
    assert QU.check_measure([5, 5, 4, 20], 0) == [5, 10, 15, 20]
    # check_division: near-equal integer split summing to v
    parts = QU.check_division(10, 3)
    assert sum(parts) == 10 and max(parts) - min(parts) <= 1
    # amplitude_est_dist: circular mod-1 distance
    assert float(QU.amplitude_est_dist(0.1, 0.9)) == pytest.approx(0.2)
    assert float(QU.amplitude_est_dist(0.4, 0.5)) == pytest.approx(0.1)
    # auxiliary_fun / vectorize_aux_fun over a QuantumState
    st = QuantumState(np.arange(4), np.ones(4) / 2.0)
    out = QU.auxiliary_fun(st, 50, key=jax.random.PRNGKey(0))
    assert len(out) == 50
    assert float(QU.vectorize_aux_fun({2: 0.25}, 2)) == pytest.approx(0.5)
    assert QU.vectorize_aux_fun({2: 0.25}, 3) == 0
    # select_labels: uniform pick from candidates; empty set raises
    picks = {int(select_labels(np.array([3, 7]),
                               key=jax.random.PRNGKey(s)))
             for s in range(20)}
    assert picks == {3, 7}
    with pytest.raises(ValueError, match="empty"):
        select_labels(np.array([]))
