"""Dataset loader tests (offline paths + cicids CSV parsing)."""

import numpy as np
import pytest

from sq_learn_tpu.datasets import (
    load_cicids,
    load_digits,
    make_blobs,
    synthetic_surrogate,
)


def test_load_digits():
    X, y = load_digits()
    assert X.shape == (1797, 64)
    assert X.dtype == np.float32
    assert set(np.unique(y)) == set(range(10))


def test_synthetic_surrogate_deterministic():
    X1, y1 = synthetic_surrogate(100, 8, 3, seed=1)
    X2, y2 = synthetic_surrogate(100, 8, 3, seed=1)
    np.testing.assert_array_equal(X1, X2)
    np.testing.assert_array_equal(y1, y2)
    assert X1.shape == (100, 8)
    assert set(np.unique(y1)) <= set(range(3))


def test_make_blobs_shapes():
    X, y = make_blobs(n_samples=50, centers=3, n_features=4, random_state=2)
    assert X.shape == (50, 4)
    assert len(np.unique(y)) <= 3


def test_cicids_csv_parsing(tmp_path):
    csv = tmp_path / "cicids_rel.csv"
    rows = ["f1,f2,f3,label"]
    rng = np.random.default_rng(0)
    for i in range(20):
        vals = rng.normal(size=3)
        label = "BENIGN" if i % 2 else "DoS"
        rows.append(",".join(f"{v:.4f}" for v in vals) + f",{label}")
    # one row with inf (CICIDS flow-rate artifact) must be dropped
    rows.append("inf,1.0,2.0,BENIGN")
    csv.write_text("\n".join(rows))
    X, y, real = load_cicids(str(csv))
    assert real
    assert X.shape == (20, 3)
    assert set(np.unique(y)) == {0, 1}
    assert np.isfinite(X).all()


def test_cicids_missing_falls_back():
    with pytest.warns(UserWarning, match="synthetic"):
        X, y, real = load_cicids("/nonexistent/file.csv", n_samples=500,
                                 n_features=10)
    assert not real
    assert X.shape == (500, 10)


@pytest.fixture(scope="module")
def mnist_bunch():
    # one surrogate generation for the whole module (70000x784 is seconds
    # of rng + ~GB intermediates; don't pay it per test)
    import warnings
    from sq_learn_tpu.datasets import fetch_openml

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return fetch_openml("mnist_784", version=1, as_frame=False)


class TestFetchFacades:
    """Drop-in fetch_openml / fetch_covtype facades (reference
    ``MnistTrial.py:10`` call shape)."""

    def test_fetch_openml_bunch(self, mnist_bunch):
        b = mnist_bunch
        assert b.data.shape == (70_000, 784)
        assert b.target.shape == (70_000,)
        assert "real" in b.details
        # attribute writes stay in sync with item access
        b2 = type(b)(b)
        b2.target = b2.target[:10]
        assert b2["target"].shape == (10,)

    def test_fetch_openml_unknown_name_or_id(self):
        from sq_learn_tpu.datasets import fetch_openml
        import pytest as _pytest

        with _pytest.raises(ValueError, match="offline"):
            fetch_openml("adult")
        with _pytest.raises(ValueError, match="offline"):
            fetch_openml(data_id=40945)

    @pytest.mark.slow
    def test_fetch_covtype(self):
        import warnings
        from sq_learn_tpu.datasets import fetch_covtype

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            X, y = fetch_covtype(return_X_y=True)
            Xs, ys = fetch_covtype(return_X_y=True, shuffle=True,
                                   random_state=0)
        assert X.shape == (581_012, 54)
        # shuffle must actually permute (sorted covertype would otherwise
        # produce single-class splits) and be seed-deterministic
        assert not np.array_equal(y[:1000], ys[:1000])
        Xs2, ys2 = fetch_covtype(return_X_y=True, shuffle=True,
                                 random_state=0)
        np.testing.assert_array_equal(ys, ys2)
