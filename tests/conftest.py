"""Test configuration.

Tests run on the XLA CPU backend with 8 virtual devices
(``--xla_force_host_platform_device_count=8``) so multi-chip sharding is
exercised without a pod — SURVEY §4's "test multi-node without a cluster"
answer.

NOTE: jax may already be imported (and JAX_PLATFORMS may point at an
accelerator) by the time pytest starts, so the platform override must go
through ``jax.config.update`` — env vars would be read too late. XLA_FLAGS
is read at backend-init time, which has not happened yet here.
"""

import os

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _clear_xla_caches_between_modules():
    """Opt-in (``SQ_TEST_CLEAR_CACHES=1``) compile-cache reset between test
    modules.

    Mitigation for the round-5 full-suite XLA segfault at [95%]
    (VERDICT.md): the CPU backend accumulated every module's compiled
    executables and died near the end of the run. Clearing per module
    bounds the cache's footprint at the cost of recompiles, so it is
    opt-in — CI (``make test`` / ``make test-timed``) sets the env var;
    the local fast loop keeps warm caches. Remove once the segfault is
    root-caused.
    """
    yield
    if os.environ.get("SQ_TEST_CLEAR_CACHES") == "1":
        jax.clear_caches()


@pytest.fixture(scope="session")
def cpu_devices():
    return jax.devices("cpu")


@pytest.fixture(scope="session")
def mesh8(cpu_devices):
    from jax.sharding import Mesh

    return Mesh(np.array(cpu_devices[:8]), ("data",))


@pytest.fixture
def key():
    return jax.random.PRNGKey(0)
