"""Serving feature-cache disk spill tier (ISSUE 13).

The tier's contract: results evicted from the RAM LRU land on disk as
digest-keyed compressed entries; a RAM miss falls through, a disk hit
verifies the FULL key (fingerprint + op + shape + dtype + content
digest) AND the payload CRC before decoding, and every failure mode —
tampered bytes, filename-hash collision, stale/foreign files — is a
miss, never an error or wrong rows. Because keys are content-addressed,
a fresh process (the registry-eviction / restart scenario) serves the
same working set without touching a kernel.
"""

import os

import numpy as np
import pytest

from sq_learn_tpu import obs
from sq_learn_tpu.models import QKMeans
from sq_learn_tpu.serving import MicroBatchDispatcher, ModelRegistry
from sq_learn_tpu.serving import cache as serve_cache
from sq_learn_tpu.utils.checkpoint import save_estimator


@pytest.fixture(autouse=True)
def _fresh_cache():
    serve_cache.clear()
    yield
    serve_cache.clear()


@pytest.fixture()
def spill_dir(tmp_path, monkeypatch):
    d = str(tmp_path / "feature_cache")
    monkeypatch.setenv("SQ_SERVE_CACHE_DIR", d)
    return d


def _entry(i, rows=6, cols=5, seed=None):
    rng = np.random.default_rng(100 + i if seed is None else seed)
    X = rng.normal(size=(rows, cols)).astype(np.float32)
    key = serve_cache.key_for(f"fp{i % 2}", "transform", X)
    val = rng.normal(size=(rows, 3)).astype(np.float32)
    return key, val


class TestSpillTier:
    def test_eviction_spills_and_disk_hit_promotes(self, spill_dir,
                                                   monkeypatch):
        monkeypatch.setenv("SQ_SERVE_CACHE_ENTRIES", "2")
        s0 = serve_cache.stats()
        entries = [_entry(i) for i in range(4)]
        for k, v in entries:
            serve_cache.store(k, v)
        s = serve_cache.stats()
        # cap 2: the first two spilled on evict
        assert s["spills"] - s0["spills"] == 2
        assert len([f for f in os.listdir(spill_dir)
                    if f.endswith(".sqc")]) == 2
        got = serve_cache.lookup(entries[0][0])
        np.testing.assert_array_equal(got, entries[0][1])
        s = serve_cache.stats()
        assert s["disk_hits"] - s0["disk_hits"] == 1
        assert s["hits"] - s0["hits"] == 1
        # promoted: the second lookup is a RAM hit
        serve_cache.lookup(entries[0][0])
        s = serve_cache.stats()
        assert s["hits"] - s0["hits"] == 2
        assert s["disk_hits"] - s0["disk_hits"] == 1

    def test_restart_scenario_ram_cleared_disk_survives(self, spill_dir,
                                                        monkeypatch):
        monkeypatch.setenv("SQ_SERVE_CACHE_ENTRIES", "1")
        entries = [_entry(i) for i in range(3)]
        for k, v in entries:
            serve_cache.store(k, v)
        s0 = serve_cache.stats()
        serve_cache.clear()  # the restart: RAM gone, disk intact
        for k, v in entries[:2]:
            got = serve_cache.lookup(k)
            assert got is not None
            np.testing.assert_array_equal(got, v)
        assert serve_cache.stats()["disk_hits"] - s0["disk_hits"] >= 2

    def test_digest_verification_tampered_payload_is_miss(self, spill_dir,
                                                          monkeypatch):
        monkeypatch.setenv("SQ_SERVE_CACHE_ENTRIES", "1")
        (k0, v0), (k1, v1) = _entry(0), _entry(1)
        serve_cache.store(k0, v0)
        serve_cache.store(k1, v1)  # evicts + spills k0
        path = serve_cache._spill_path(spill_dir, serve_cache._key_json(k0))
        data = open(path, "rb").read()
        with open(path, "wb") as fh:  # flip payload tail bytes
            fh.write(data[:-4] + bytes(4))
        s0 = serve_cache.stats()
        serve_cache.clear()
        assert serve_cache.lookup(k0) is None
        assert serve_cache.stats()["disk_hits"] == s0["disk_hits"]

    def test_header_key_mismatch_is_miss(self, spill_dir, monkeypatch):
        """A file parked at the key's filename but carrying a different
        full key (hash collision / stale tooling) must never serve."""
        monkeypatch.setenv("SQ_SERVE_CACHE_ENTRIES", "1")
        (k0, v0), (k1, v1) = _entry(0), _entry(1)
        serve_cache.store(k0, v0)
        serve_cache.store(k1, v1)  # spills k0
        spilled = serve_cache._spill_path(spill_dir,
                                          serve_cache._key_json(k0))
        # park k0's file bytes at k1's filename: full-key check must miss
        alias = serve_cache._spill_path(spill_dir,
                                        serve_cache._key_json(k1))
        os.replace(spilled, alias)
        serve_cache.clear()
        assert serve_cache.lookup(k1) is None
        assert serve_cache.lookup(k0) is None

    def test_no_dir_no_disk_tier(self, tmp_path, monkeypatch):
        monkeypatch.delenv("SQ_SERVE_CACHE_DIR", raising=False)
        monkeypatch.setenv("SQ_SERVE_CACHE_ENTRIES", "1")
        s0 = serve_cache.stats()
        (k0, v0), (k1, v1) = _entry(0), _entry(1)
        serve_cache.store(k0, v0)
        serve_cache.store(k1, v1)
        assert serve_cache.stats()["spills"] == s0["spills"]
        assert serve_cache.lookup(k0) is None

    def test_spill_all_persists_resident_entries(self, spill_dir):
        entries = [_entry(i) for i in range(3)]
        for k, v in entries:
            serve_cache.store(k, v)
        assert serve_cache.spill_all() == 3
        serve_cache.clear()
        for k, v in entries:
            np.testing.assert_array_equal(serve_cache.lookup(k), v)

    def test_clear_disk_true_drops_files(self, spill_dir, monkeypatch):
        monkeypatch.setenv("SQ_SERVE_CACHE_ENTRIES", "1")
        for i in range(3):
            serve_cache.store(*_entry(i))
        assert any(f.endswith(".sqc") for f in os.listdir(spill_dir))
        serve_cache.clear(disk=True)
        assert not any(f.endswith(".sqc") for f in os.listdir(spill_dir))

    def test_counters_flush_to_recorder(self, spill_dir, tmp_path,
                                        monkeypatch):
        monkeypatch.setenv("SQ_SERVE_CACHE_ENTRIES", "1")
        serve_cache.flush_counters()  # drain older tests' pendings
        rec = obs.enable(str(tmp_path / "obs.jsonl"))
        try:
            (k0, v0), (k1, v1) = _entry(0), _entry(1)
            serve_cache.store(k0, v0)
            serve_cache.store(k1, v1)
            serve_cache.lookup(k0)  # disk hit
            serve_cache.flush_counters()
            assert rec.counters.get("serving.cache_spills", 0) >= 1
            assert rec.counters.get("serving.cache_disk_hits", 0) == 1
            assert rec.counters.get("serving.cache_hits", 0) == 1
        finally:
            obs.disable()


class TestDispatcherSpill:
    def test_end_to_end_evict_then_disk_hit_bit_parity(self, spill_dir,
                                                       monkeypatch,
                                                       tmp_path):
        """The smoke scenario in-process: tiny RAM LRU, distinct
        transform payloads force an eviction, re-requesting the evicted
        payload serves a digest-verified disk hit bit-equal to the
        computed response — and a registry re-load (same checkpoint =
        same fingerprint) still hits, because keys are content-
        addressed, not tenant-addressed."""
        import jax

        jax.config.update("jax_platforms", "cpu")
        monkeypatch.setenv("SQ_SERVE_CACHE_ENTRIES", "2")
        rng = np.random.default_rng(0)
        X = (rng.normal(size=(300, 8))
             + 4.0 * rng.integers(0, 3, size=(300, 1))).astype(np.float32)
        ckpt = save_estimator(QKMeans(n_clusters=3, random_state=0).fit(X),
                              str(tmp_path / "ckpt"))
        reg = ModelRegistry()
        reg.register("t", ckpt)
        payloads = [rng.normal(size=(4, 8)).astype(np.float32)
                    for _ in range(3)]
        d = MicroBatchDispatcher(reg, background=False)
        ref = [d.serve("t", "transform", p) for p in payloads]
        assert serve_cache.stats()["spills"] >= 1
        dh0 = serve_cache.stats()["disk_hits"]
        again = d.serve("t", "transform", payloads[0])
        d.close()
        assert serve_cache.stats()["disk_hits"] == dh0 + 1
        np.testing.assert_array_equal(again, ref[0])
        # fresh registry + RAM cache, same checkpoint: disk still serves
        serve_cache.clear()
        reg2 = ModelRegistry()
        reg2.register("renamed", ckpt)
        d2 = MicroBatchDispatcher(reg2, background=False)
        out = d2.serve("renamed", "transform", payloads[1])
        d2.close()
        np.testing.assert_array_equal(out, ref[1])
        assert serve_cache.stats()["disk_hits"] >= dh0 + 2
