"""set_config(device=...) dispatch wiring (VERDICT round 1 weak #3).

Under the conftest the process has 8 virtual CPU devices, so 'cpu:N'
placement is observable: committed arrays land on a specific device and
every downstream jit executes there. Parity contract: a δ=0 fit under
device='cpu' must equal the default-placement fit bit-for-bit.
"""

import numpy as np
import jax
import pytest
import sklearn.datasets

from sq_learn_tpu import config_context, resolve_device
from sq_learn_tpu._config import as_device_array
from sq_learn_tpu.models import KMeans, QKMeans


@pytest.fixture(scope="module")
def blobs():
    X, y = sklearn.datasets.make_blobs(
        n_samples=300, centers=4, cluster_std=0.7, random_state=2)
    return X.astype(np.float32), y


def test_as_device_array_commits_to_configured_device():
    cpus = jax.devices("cpu")
    with config_context(device="cpu:3"):
        arr = as_device_array(np.ones(8, np.float32))
        assert arr.devices() == {cpus[3]}
    with config_context(device="cpu"):
        arr = as_device_array(np.ones(8, np.float32))
        assert arr.devices() == {cpus[0]}


def test_auto_leaves_placement_uncommitted():
    with config_context(device="auto"):
        arr = as_device_array(np.ones(8, np.float32))
    # uncommitted default placement — jit may move it freely
    assert arr.devices() == {jax.devices()[0]}


def test_resolve_device_variants():
    cpus = jax.devices("cpu")
    with config_context(device="cpu:2"):
        assert resolve_device() == cpus[2]
    with config_context(device="cpu"):
        assert resolve_device() == cpus[0]
    with config_context(device="tpu"):
        with pytest.raises(RuntimeError, match="no accelerator"):
            resolve_device()
    with config_context(device="cpu:99"):
        with pytest.raises(RuntimeError, match="only"):
            resolve_device()


def test_set_config_rejects_bogus_device():
    from sq_learn_tpu import set_config

    for bogus in ("gpu", "auto:1", "cpu:abc", "cpu:-1", "cpu:", 3):
        with pytest.raises(ValueError, match="device must be"):
            set_config(device=bogus)


class TestHostPut:
    """Streamed host→device placement (the ≥200 MB relay-wedge dodge),
    through the internal ``_put_host`` that ``as_device_array`` routes
    every placement through (the public streamed surface is
    ``streaming.streamed_resident_put``; the removed ``chunked_device_put``
    wrapper is pinned below to fail loudly).

    On the CPU backend the slicing only engages when max_bytes is passed
    explicitly, which is exactly how these tests force the assembly path."""

    def test_parity_with_plain_asarray(self):
        from sq_learn_tpu._config import _put_host

        x = np.random.RandomState(0).randn(97, 13).astype(np.float32)
        out = _put_host(x, None, max_bytes=512)  # ~10 rows/slice
        np.testing.assert_array_equal(np.asarray(out), x)
        assert out.dtype == jax.numpy.asarray(x).dtype

    def test_committed_placement_survives_chunking(self):
        from sq_learn_tpu._config import _put_host

        cpus = jax.devices("cpu")
        x = np.ones((64, 8), np.float32)
        out = _put_host(x, cpus[2], max_bytes=256)
        assert out.devices() == {cpus[2]}
        np.testing.assert_array_equal(np.asarray(out), x)

    def test_dtype_canonicalization_matches_asarray(self):
        from sq_learn_tpu._config import _put_host

        x64 = np.random.RandomState(1).randn(40, 4)  # float64 host data
        out = _put_host(x64, None, max_bytes=128)
        expected = jax.numpy.asarray(x64)
        assert out.dtype == expected.dtype
        np.testing.assert_array_equal(np.asarray(out), np.asarray(expected))

    def test_one_dim_and_small_inputs_pass_through(self):
        from sq_learn_tpu._config import _put_host

        v = np.arange(1000, dtype=np.float32)
        np.testing.assert_array_equal(
            np.asarray(_put_host(v, None, max_bytes=400)), v)
        small = np.ones((3, 3), np.float32)
        np.testing.assert_array_equal(
            np.asarray(_put_host(small, None)), small)

    def test_removed_chunked_device_put_raises_with_pointer(self):
        """ISSUE 10 satellite: the long-deprecated compatibility wrapper
        is gone; external callers get a loud, actionable error instead of
        silently changed semantics."""
        from sq_learn_tpu._config import chunked_device_put

        with pytest.raises(RuntimeError, match="streamed_resident_put"):
            chunked_device_put(np.ones((4, 4), np.float32))

    def test_cpu_targets_skip_slicing_by_default(self, monkeypatch):
        """With the default max_bytes a CPU-bound transfer stays one piece
        even when the array exceeds the threshold (host→host copies can't
        wedge a relay). Slicing is observable as device_put call count."""
        import sq_learn_tpu._config as cfg

        monkeypatch.setattr(cfg, "_TRANSFER_CHUNK_BYTES", 128)
        calls = []
        real_put = jax.device_put
        monkeypatch.setattr(jax, "device_put",
                            lambda *a, **k: (calls.append(1),
                                             real_put(*a, **k))[1])
        x = np.random.RandomState(2).randn(50, 6).astype(np.float32)
        with config_context(device="cpu:1"):
            out = as_device_array(x)
        assert len(calls) == 1, f"expected ONE transfer, saw {len(calls)}"
        assert out.devices() == {jax.devices("cpu")[1]}
        np.testing.assert_array_equal(np.asarray(out), x)

    def test_single_row_larger_than_budget_still_transfers(self):
        from sq_learn_tpu._config import _put_host

        x = np.random.RandomState(3).randn(4, 64).astype(np.float32)
        out = _put_host(x, None, max_bytes=16)  # 256 B rows
        np.testing.assert_array_equal(np.asarray(out), x)


def test_fit_computation_runs_on_configured_device(blobs):
    """The committed input pins the fused prestats jit to the chosen chip."""
    from sq_learn_tpu.models.qkmeans import fit_prestats

    X, _ = blobs
    with config_context(device="cpu:5"):
        stats = fit_prestats(as_device_array(X))
    assert stats["Xc"].devices() == {jax.devices("cpu")[5]}


def test_delta_zero_fit_parity_across_devices(blobs):
    """VERDICT task 4 'done' criterion: δ=0 fit under device='cpu' equals
    the default-placement fit."""
    X, _ = blobs
    base = KMeans(n_clusters=4, n_init=2, random_state=0).fit(X)
    with config_context(device="cpu:1"):
        pinned = KMeans(n_clusters=4, n_init=2, random_state=0).fit(X)
    np.testing.assert_array_equal(base.labels_, pinned.labels_)
    np.testing.assert_allclose(base.cluster_centers_,
                               pinned.cluster_centers_, rtol=1e-6)
    assert base.inertia_ == pytest.approx(pinned.inertia_, rel=1e-6)


def test_quantum_fit_works_under_pinned_device(blobs):
    import warnings

    X, y = blobs
    from sq_learn_tpu.metrics import adjusted_rand_score

    with config_context(device="cpu:2"), warnings.catch_warnings():
        warnings.simplefilter("ignore")
        qm = QKMeans(n_clusters=4, delta=0.5, true_distance_estimate=False,
                     n_init=1, random_state=0).fit(X)
    assert float(adjusted_rand_score(qm.labels_, y)) > 0.9


def test_other_estimators_respect_device(blobs):
    X, y = blobs
    from sq_learn_tpu.models import QPCA, TruncatedSVD
    from sq_learn_tpu.models.neighbors import KNeighborsClassifier

    X6 = np.random.RandomState(0).randn(120, 6).astype(np.float32)
    with config_context(device="cpu:4"):
        pca = QPCA(n_components=2).fit(X)
        assert pca.explained_variance_.shape == (2,)
        tsvd = TruncatedSVD(n_components=3).fit(X6)
        assert tsvd.components_.shape == (3, 6)
        knn = KNeighborsClassifier(n_neighbors=3).fit(X, (y % 2))
        assert knn.X_fit_.devices() == {jax.devices("cpu")[4]}
        assert knn.score(X[:50], (y % 2)[:50]) > 0.5


class TestTinyFitHostRouting:
    """Size-aware dispatch (VERDICT r3 next #4): digit-scale fits on a
    remote accelerator are pure tunnel latency, so fit() routes them to
    the host engines — explicitly, testably, instead of depending on
    link health. No accelerator exists under the test conftest, so the
    backend is faked at the predicate's seam (jax.default_backend)."""

    def test_policy_predicate(self, monkeypatch):
        from sq_learn_tpu import _config

        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        digits, mnist = 1797 * 64, 70_000 * 784
        assert _config.route_tiny_fit_to_host(digits)
        assert not _config.route_tiny_fit_to_host(mnist)
        # explicit pins are respected in BOTH directions: 'tpu' = the
        # user wants the chip timed, 'cpu' already routes everything
        with config_context(device="tpu"):
            assert not _config.route_tiny_fit_to_host(digits)
        with config_context(device="cpu"):
            assert not _config.route_tiny_fit_to_host(digits)
        # env kill-switch
        monkeypatch.setattr(_config, "_TINY_FIT_ELEMENTS", 0)
        assert not _config.route_tiny_fit_to_host(digits)

    def test_policy_off_on_cpu_backend(self):
        from sq_learn_tpu import _config

        # the real test backend IS cpu: never route (nothing to dodge)
        assert not _config.route_tiny_fit_to_host(1797 * 64)

    def test_backend_probe_never_forces_init(self, monkeypatch):
        """ADVICE r4 #2: the routing decision must not be the thing that
        first initializes a (possibly wedged) accelerator backend — with
        backends uninitialized and a platform spec pinned, the answer
        comes from the spec alone."""
        from jax._src import xla_bridge

        from sq_learn_tpu import _config

        # initialized tier: authoritative answer
        assert (_config._default_backend_platform_no_init()
                == jax.default_backend())
        # uninitialized tier: first entry of the jax_platforms spec (the
        # conftest pins 'cpu'); default_backend() must NOT be consulted
        monkeypatch.setattr(xla_bridge, "backends_are_initialized",
                            lambda: False)
        monkeypatch.setattr(jax, "default_backend", lambda: (_ for _ in ())
                            .throw(AssertionError("forced backend init")))
        spec_first = jax.config.jax_platforms.split(",")[0].strip()
        assert (_config._default_backend_platform_no_init() == spec_first)

    def test_fit_routes_and_matches_unrouted_results(self, blobs,
                                                     monkeypatch):
        X, _ = blobs
        from sq_learn_tpu import _config

        base = QKMeans(n_clusters=4, n_init=2, delta=0.5,
                       true_distance_estimate=False, random_state=0).fit(X)
        assert base.fit_backend_ == "cpu"

        # force the routing decision on (as a remote-accelerator process
        # would take it); on this CPU host the rerouted fit must be the
        # same computation, so results match the unrouted fit exactly
        monkeypatch.setattr(_config, "route_tiny_fit_to_host",
                            lambda n: True)
        routed = QKMeans(n_clusters=4, n_init=2, delta=0.5,
                         true_distance_estimate=False, random_state=0).fit(X)
        assert routed.fit_backend_ == "cpu:tiny-routed"
        np.testing.assert_array_equal(routed.labels_, base.labels_)
        np.testing.assert_allclose(routed.cluster_centers_,
                                   base.cluster_centers_, rtol=1e-6)

    def test_explicit_settings_bypass_routing(self, blobs, monkeypatch):
        X, _ = blobs
        from sq_learn_tpu import _config

        monkeypatch.setattr(_config, "route_tiny_fit_to_host",
                            lambda n: True)
        # forcing a kernel choice opts out of the size heuristic
        est = QKMeans(n_clusters=4, n_init=1, delta=0.0, use_pallas=False,
                      random_state=0).fit(X)
        assert est.fit_backend_ != "cpu:tiny-routed"


class TestTinyRoutingExtendedSurfaces:
    """Round-5 scope extension (VERDICT r4 next #4): the size-aware host
    routing covers every tiny dispatch surface, not just QKMeans.fit —
    QPCA.fit, MiniBatchQKMeans.fit/partial_fit, and the KNN search."""

    def test_qpca_fit_routes_and_matches(self, blobs, monkeypatch):
        from sq_learn_tpu import _config
        from sq_learn_tpu.models import QPCA

        X, _ = blobs
        base = QPCA(n_components=2, random_state=0).fit(X)
        assert base.fit_backend_ == "cpu"
        monkeypatch.setattr(_config, "route_tiny_fit_to_host",
                            lambda n: True)
        routed = QPCA(n_components=2, random_state=0).fit(X)
        assert routed.fit_backend_ == "cpu:tiny-routed"
        np.testing.assert_allclose(routed.components_, base.components_,
                                   rtol=1e-6)
        np.testing.assert_allclose(
            routed.explained_variance_, base.explained_variance_, rtol=1e-6)

    def test_qpca_mesh_bypasses_routing(self, blobs, monkeypatch):
        from sq_learn_tpu import _config
        from sq_learn_tpu.models import QPCA
        from sq_learn_tpu.parallel import make_mesh

        X, _ = blobs
        monkeypatch.setattr(_config, "route_tiny_fit_to_host",
                            lambda n: True)
        est = QPCA(n_components=2, random_state=0,
                   mesh=make_mesh(jax.devices("cpu")[:8])).fit(X)
        assert est.fit_backend_ != "cpu:tiny-routed"

    def test_minibatch_fit_routes_and_matches(self, blobs, monkeypatch):
        from sq_learn_tpu import _config
        from sq_learn_tpu.models import MiniBatchQKMeans

        X, _ = blobs
        kw = dict(n_clusters=4, batch_size=64, random_state=0, delta=0.0)
        base = MiniBatchQKMeans(**kw).fit(X)
        assert base.fit_backend_ == "cpu"
        monkeypatch.setattr(_config, "route_tiny_fit_to_host",
                            lambda n: True)
        routed = MiniBatchQKMeans(**kw).fit(X)
        assert routed.fit_backend_ == "cpu:tiny-routed"
        np.testing.assert_allclose(routed.cluster_centers_,
                                   base.cluster_centers_, rtol=1e-6)

    def test_minibatch_partial_fit_routes_and_matches(self, blobs,
                                                      monkeypatch):
        from sq_learn_tpu import _config
        from sq_learn_tpu.models import MiniBatchQKMeans

        X, _ = blobs
        kw = dict(n_clusters=4, batch_size=64, random_state=0, delta=0.0)
        base = MiniBatchQKMeans(**kw).partial_fit(X).partial_fit(X)
        assert base.fit_backend_ == "cpu"
        monkeypatch.setattr(_config, "route_tiny_fit_to_host",
                            lambda n: True)
        routed = MiniBatchQKMeans(**kw).partial_fit(X).partial_fit(X)
        assert routed.fit_backend_ == "cpu:tiny-routed"
        np.testing.assert_allclose(routed.cluster_centers_,
                                   base.cluster_centers_, rtol=1e-5)

    def test_knn_search_routes_off_the_device_path(self, blobs, monkeypatch):
        from sq_learn_tpu import _config
        from sq_learn_tpu.models import KNeighborsClassifier

        X, y = blobs
        knn = KNeighborsClassifier(n_neighbors=3).fit(X, y)
        want = knn.predict(X[:20])

        # fake a remote-accelerator process: the host fast path disengages
        # (backend != cpu) and the tiny-routing seam takes over
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        monkeypatch.setattr(_config, "route_tiny_fit_to_host",
                            lambda n: True)
        knn._device_search = lambda *a: (_ for _ in ()).throw(
            AssertionError("tiny predict reached the device path"))
        got = knn.predict(X[:20])
        np.testing.assert_array_equal(got, want)

    def test_compute_dtype_bypasses_routing(self, blobs, monkeypatch):
        """An explicit compute_dtype is a chip-path precision hint: the
        routed surfaces must not silently reroute it to the host (the
        bypass contract docs/api.md promises, uniform across surfaces)."""
        import warnings

        from sq_learn_tpu import _config
        from sq_learn_tpu.models import QPCA

        X, _ = blobs
        monkeypatch.setattr(_config, "route_tiny_fit_to_host",
                            lambda n: True)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            pca = QPCA(n_components=2, compute_dtype="bfloat16",
                       random_state=0).fit(X)
            km = QKMeans(n_clusters=4, n_init=1, delta=0.0,
                         compute_dtype="bfloat16", random_state=0).fit(X)
        assert pca.fit_backend_ != "cpu:tiny-routed"
        assert km.fit_backend_ != "cpu:tiny-routed"

    def test_qkmeans_predict_and_score_route(self, blobs, monkeypatch):
        from sq_learn_tpu import _config

        X, _ = blobs
        est = QKMeans(n_clusters=4, n_init=1, delta=0.0,
                      random_state=0).fit(X)
        want_labels = est.predict(X[:30])
        want_score = est.score(X[:30])
        # fake a remote-accelerator process; the host fast path must be
        # reached through the tiny-routing seam, never the device dispatch
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        monkeypatch.setattr(_config, "route_tiny_fit_to_host",
                            lambda n: True)
        from sq_learn_tpu.models import qkmeans as qk

        def boom(*a, **k):
            raise AssertionError("tiny predict reached the device path")

        monkeypatch.setattr(qk, "e_step_jit", boom)
        np.testing.assert_array_equal(est.predict(X[:30]), want_labels)
        assert est.score(X[:30]) == pytest.approx(want_score, rel=1e-6)

    def test_knn_explicit_settings_bypass_routing(self, blobs, monkeypatch):
        from sq_learn_tpu import _config
        from sq_learn_tpu.models import KNeighborsClassifier

        X, y = blobs
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        monkeypatch.setattr(_config, "route_tiny_fit_to_host",
                            lambda n: True)
        knn = KNeighborsClassifier(n_neighbors=3, use_pallas=False)
        knn.fit(X, y)
        # an explicit kernel choice opts out of the size heuristic: the
        # search must go to the device path, not the host engines
        assert knn._tiny_routed_search(X[:20], 3) is None


class TestFitBackendProvenance:
    """fit_backend_ is assigned only after a successful fit (ADVICE r4
    #1): a raise mid-fit must not leave a fitted-looking public attribute
    for checkpoint.save_estimator to serialize."""

    def test_qkmeans_failed_fit_leaves_no_backend(self, blobs):
        X, _ = blobs
        est = QKMeans(n_clusters=2, delta=0.0, intermediate_error=True)
        with pytest.raises(ValueError, match="intermediate_error"):
            est.fit(X)  # raises inside _fit_impl, after dispatch decided
        assert not hasattr(est, "fit_backend_")

    def test_qpca_failed_fit_leaves_no_backend(self, blobs):
        from sq_learn_tpu.models import QPCA

        X, _ = blobs
        est = QPCA(n_components=2, svd_solver="bogus")
        with pytest.raises(ValueError, match="Unrecognized svd_solver"):
            est.fit(X)
        assert not hasattr(est, "fit_backend_")


class TestTinyRoutingTransformSurfaces:
    """Round-6 scope closure (VERDICT r5 weak #3 / next #4): the
    transform-shaped surfaces route too — QKMeans.transform,
    QPCA.transform (and through it fit_transform's transform half), and
    QLSSVC.predict — with the same bypass contract as the fit-shaped
    ones. The spy pattern: host_routed_scope must be entered on the
    routed path, and the routed result must equal the unrouted one."""

    def _spy_scope(self, monkeypatch):
        from sq_learn_tpu import _config

        calls = []
        real = _config.host_routed_scope

        def spy():
            calls.append(1)
            return real()

        monkeypatch.setattr(_config, "host_routed_scope", spy)
        return calls

    def test_qkmeans_transform_routes_and_matches(self, blobs, monkeypatch):
        from sq_learn_tpu import _config

        X, _ = blobs
        est = QKMeans(n_clusters=4, n_init=1, delta=0.0,
                      random_state=0).fit(X)
        want = est.transform(X[:25])
        calls = self._spy_scope(monkeypatch)
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        monkeypatch.setattr(_config, "route_tiny_fit_to_host",
                            lambda n: True)
        got = est.transform(X[:25])
        assert calls, "tiny transform never entered host_routed_scope"
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_qpca_transform_routes_and_matches(self, blobs, monkeypatch):
        from sq_learn_tpu import _config
        from sq_learn_tpu.models import QPCA

        X, _ = blobs
        est = QPCA(n_components=2, random_state=0).fit(X)
        want = est.transform(X[:25])
        calls = self._spy_scope(monkeypatch)
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        monkeypatch.setattr(_config, "route_tiny_fit_to_host",
                            lambda n: True)
        got = est.transform(X[:25])
        assert calls, "tiny transform never entered host_routed_scope"
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_qpca_fit_transform_halves_both_route(self, blobs, monkeypatch):
        from sq_learn_tpu import _config
        from sq_learn_tpu.models import QPCA

        X, _ = blobs
        want = QPCA(n_components=2, random_state=0).fit_transform(X)
        calls = self._spy_scope(monkeypatch)
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        monkeypatch.setattr(_config, "route_tiny_fit_to_host",
                            lambda n: True)
        est = QPCA(n_components=2, random_state=0)
        got = est.fit_transform(X)
        assert est.fit_backend_ == "cpu:tiny-routed"
        assert len(calls) >= 2  # the fit half AND the transform half
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_qlssvc_predict_routes_and_matches(self, blobs, monkeypatch):
        from sq_learn_tpu import _config
        from sq_learn_tpu.models import QLSSVC

        X, _ = blobs
        y = np.where(X[:, 0] > X[:, 0].mean(), 1.0, -1.0)
        clf = QLSSVC(absolute_error=0.01, random_state=0).fit(X, y)
        want = clf.predict(X[:20])
        calls = self._spy_scope(monkeypatch)
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        monkeypatch.setattr(_config, "route_tiny_fit_to_host",
                            lambda n: True)
        got = clf.predict(X[:20])
        assert calls, "tiny predict never entered host_routed_scope"
        np.testing.assert_array_equal(got, want)

    def test_qkmeans_transform_compute_dtype_bypasses(self, blobs,
                                                      monkeypatch):
        import warnings

        from sq_learn_tpu import _config

        X, _ = blobs
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            est = QKMeans(n_clusters=4, n_init=1, delta=0.0,
                          compute_dtype="bfloat16", random_state=0).fit(X)
        calls = self._spy_scope(monkeypatch)
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        monkeypatch.setattr(_config, "route_tiny_fit_to_host",
                            lambda n: True)
        est.transform(X[:25])
        assert not calls, "compute_dtype hint must bypass tiny routing"

    def test_qpca_transform_mesh_bypasses(self, blobs, monkeypatch):
        from sq_learn_tpu import _config
        from sq_learn_tpu.models import QPCA
        from sq_learn_tpu.parallel import make_mesh

        X, _ = blobs
        est = QPCA(n_components=2, random_state=0,
                   mesh=make_mesh(jax.devices("cpu")[:8])).fit(X)
        calls = self._spy_scope(monkeypatch)
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        monkeypatch.setattr(_config, "route_tiny_fit_to_host",
                            lambda n: True)
        est.transform(X[:25])
        assert not calls, "an explicit mesh must bypass tiny routing"
