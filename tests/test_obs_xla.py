"""ISSUE 4's analysis layer: XLA cost/memory accounting (obs.xla),
Chrome trace export (obs.trace), the report CLI (obs.report), and the
perf-regression gate (obs.regress) — plus the v2 schema envelope."""

import json

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sq_learn_tpu import obs
from sq_learn_tpu.obs.schema import (SCHEMA_VERSION, validate_jsonl,
                                     validate_record)
from sq_learn_tpu.utils.profiling import matmul_flops


@pytest.fixture
def run():
    rec = obs.enable()
    yield rec
    obs.disable()


# -- xla cost accounting -----------------------------------------------------


def test_capture_records_finite_cost_and_memory(run):
    f = jax.jit(lambda a, b: a @ b)
    x, y = jnp.ones((64, 32)), jnp.ones((32, 16))
    entry = obs.xla.capture("t.matmul", f, x, y)
    assert entry is not None
    assert entry["site"] == "t.matmul"
    assert "float32[64,32]" in entry["signature"]
    assert np.isfinite(entry["flops"]) and entry["flops"] > 0
    assert np.isfinite(entry["bytes_accessed"])
    assert entry["peak_bytes"] > 0
    assert run.xla_cost_records == [entry]


def test_capture_dedups_per_site_signature(run):
    f = jax.jit(lambda a: a * 2)
    x = jnp.ones((8,))
    assert obs.xla.capture("t.dedup", f, x) is not None
    assert obs.xla.capture("t.dedup", f, x) is None  # same signature
    assert obs.xla.capture("t.dedup", f, jnp.ones((16,))) is not None
    assert obs.xla.capture("t.other", f, x) is not None  # site re-keys
    assert len(run.xla_cost_records) == 3


def test_capture_extra_key_splits_identical_arg_signatures(run):
    x = jnp.ones((8,))
    for mode in ("a", "b"):
        f = jax.jit(lambda v, _m=mode: v + (1.0 if _m == "a" else 2.0))
        obs.xla.capture("t.closure", f, x, _extra_key=mode)
    assert len(run.xla_cost_records) == 2


def test_capture_noop_when_disabled():
    obs.disable()
    # fn=None would explode on any real work: the disabled path must
    # return before touching it
    assert obs.xla.capture("t.off", None) is None
    assert obs.xla.records() == []
    assert obs.xla.flops_of("t.off") is None
    assert obs.xla.peak_bytes() is None


def test_capture_degrades_on_unlowerable_callable(run):
    entry = obs.xla.capture("t.broken", object())
    assert entry is not None and entry["flops"] is None
    assert "error" in entry
    # and the record still validates (null costs are legal)
    assert validate_record(run.xla_cost_records[0]) == []


def test_matmul_flops_parity_with_hand_formula(run):
    """The accounting must be wired to the real computation: XLA's FLOP
    count for an (m,k)@(k,n) GEMM agrees with utils.profiling's
    2·m·k·n within 2x (satellite: pins against a stale lowering)."""
    m, k, n = 128, 64, 32
    f = jax.jit(lambda a, b: a @ b)
    entry = obs.xla.capture("t.parity", f, jnp.ones((m, k)),
                            jnp.ones((k, n)))
    hand = matmul_flops(m, k, n)
    assert hand / 2 <= entry["flops"] <= hand * 2


def test_streaming_kernels_record_cost_with_parity(run):
    """The instrumented streamed Gram kernel records one xla_cost per
    (bucket, dtype) signature, and its FLOPs agree with the tile-GEMM
    hand formula within 2x."""
    from sq_learn_tpu import streaming

    X = np.random.default_rng(0).normal(size=(512, 16)).astype(np.float32)
    streaming.streamed_centered_gram(X, max_bytes=8 * 1024)
    recs = [r for r in run.xla_cost_records
            if r["site"] == "streaming.gram_colsum"]
    assert recs, "streamed Gram pass recorded no xla_cost"
    rows = 8 * 1024 // (16 * 4)  # tile rows under the byte cap
    hand = matmul_flops(16, rows, 16)  # tile.T @ tile per tile
    assert hand / 2 <= recs[0]["flops"] <= hand * 2
    # watchdog keeps observing through the wrapper (compiles may be 0
    # here: an earlier test in the same process can have warmed this
    # bucket's cache, and run-scoped counts are baselined at track())
    rep = obs.watchdog.report()["streaming.gram_colsum"]
    assert rep["observations"] >= 1 and not rep["over_budget"]
    sizes = streaming.kernel_cache_sizes()
    assert sizes["gram_colsum"] >= 1


def test_instrument_forwards_cache_size_and_result():
    f = jax.jit(lambda x: x + 1)
    wrapped = obs.xla.instrument("t.wrap", f)
    out = wrapped(jnp.ones((4,)))
    np.testing.assert_allclose(np.asarray(out), 2.0)
    assert int(wrapped._cache_size()) == int(f._cache_size())


def test_mfu_uses_measured_flops_for_site(run, monkeypatch):
    from sq_learn_tpu.utils import profiling

    monkeypatch.setenv("SQ_TPU_PEAK_FLOPS", "1e12")
    f = jax.jit(lambda a, b: a @ b)
    entry = obs.xla.capture("t.mfu", f, jnp.ones((64, 64)),
                            jnp.ones((64, 64)))
    # hand flops argument is deliberately nonsense: site= must override
    value = profiling.mfu(1.0, 0.5, site="t.mfu")
    assert value == pytest.approx((entry["flops"] / 0.5) / 1e12)
    gauge = [r for r in run.gauge_events
             if r["name"] == "profiling.mfu"][-1]
    assert gauge["attrs"]["source"] == "xla_cost"


def test_snapshot_carries_peak_hbm_and_measured_mfu(run):
    from sq_learn_tpu.utils import profiling

    snap = obs.snapshot()
    assert snap["peak_hbm_bytes"] is None
    assert snap["measured_mfu"] is None
    assert snap["xla_cost_records"] == 0
    f = jax.jit(lambda a, b: a @ b)
    obs.xla.capture("t.snap", f, jnp.ones((32, 32)), jnp.ones((32, 32)))
    profiling.mfu(1e9, 1.0)  # finite on the CPU backend (host estimate)
    snap = obs.snapshot()
    assert snap["peak_hbm_bytes"] > 0
    assert isinstance(snap["measured_mfu"], float)
    assert snap["xla_cost_records"] == 1


# -- v2 schema ---------------------------------------------------------------


def test_schema_v5_envelope_and_new_types(run, tmp_path):
    path = str(tmp_path / "v5.jsonl")
    obs.enable(path)
    try:
        with obs.span("s"):
            pass
        f = jax.jit(lambda x: x * 3)
        obs.xla.capture("t.schema", f, jnp.ones((4,)))
    finally:
        obs.disable()
    recs = [json.loads(l) for l in open(path)]
    assert all(r["v"] == SCHEMA_VERSION
               and r["schema_version"] == SCHEMA_VERSION
               for r in recs)
    summary = validate_jsonl(path)
    assert summary["errors"] == []
    assert summary["by_type"]["xla_cost"] == 1


def test_schema_validates_regression_records():
    good = {"v": 2, "schema_version": 2, "ts": 0.0, "type": "regression",
            "gate": "latency", "metric": "m", "verdict": "green",
            "current": 1.0, "reference": 1.1, "tolerance": 2.25}
    assert validate_record(good) == []
    bad = dict(good, verdict="maybe")
    assert validate_record(bad)


def test_schema_rejects_unknown_version_and_mismatch():
    assert validate_record({"v": 99, "schema_version": 99, "ts": 0.0,
                            "type": "gauge", "name": "g", "value": 1})
    assert validate_record({"v": 2, "schema_version": 1, "ts": 0.0,
                            "type": "gauge", "name": "g", "value": 1})
    # v2+ records must carry the schema_version alias
    assert validate_record({"v": 2, "ts": 0.0, "type": "gauge",
                            "name": "g", "value": 1})
    assert validate_record({"v": 7, "ts": 0.0, "type": "gauge",
                            "name": "g", "value": 1})
    # v1 lines (pre-v2 files) still validate without it, and v2..v8
    # lines (pre-v9 files) validate with it
    assert validate_record({"v": 1, "ts": 0.0, "type": "gauge",
                            "name": "g", "value": 1}) == []
    for v in (2, 3, 4, 5, 6, 7, 8):
        assert validate_record({"v": v, "schema_version": v, "ts": 0.0,
                                "type": "gauge", "name": "g",
                                "value": 1}) == []


# -- chrome trace export -----------------------------------------------------


def _jsonl(path, records):
    with open(path, "w") as fh:
        for r in records:
            fh.write(json.dumps(r) + "\n")


def _env(rec):
    out = {"v": 2, "schema_version": 2, "ts": rec.pop("ts", 100.0)}
    out.update(rec)
    return out


def test_trace_structurally_valid_and_multiprocess(tmp_path):
    """Round-trips a run containing fault/breaker records from two
    processes onto pid/tid lanes — the acceptance shape of the trace
    exporter."""
    from sq_learn_tpu.obs.trace import write_trace

    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    _jsonl(a, [
        _env({"type": "meta", "pid": 11, "schema": 2, "ts": 100.0}),
        _env({"type": "span", "name": "fit", "seq": 1, "dur_s": 0.5,
              "depth": 0, "parent": None, "synced": True, "ts": 101.0}),
        _env({"type": "span", "name": "tile", "seq": 2, "dur_s": 0.1,
              "depth": 1, "parent": 1, "synced": False, "ts": 100.8}),
        _env({"type": "counter", "name": "streaming.transfer_bytes",
              "value": 1024, "delta": 1024, "ts": 100.7}),
        _env({"type": "fault", "kind": "put_fail", "tile": 3,
              "ts": 100.75}),
        _env({"type": "breaker", "state": "open", "prev": "closed",
              "reason": "k_failures", "consecutive": 3, "ts": 100.9}),
    ])
    _jsonl(b, [
        _env({"type": "meta", "pid": 22, "schema": 2, "ts": 100.0}),
        _env({"type": "probe", "outcome": "ok", "latency_s": 5.0,
              "platform": "axon", "ts": 105.0}),
        _env({"type": "xla_cost", "site": "s", "signature": "(f32[4])",
              "flops": 8.0, "bytes_accessed": 32.0, "peak_bytes": 64,
              "ts": 106.0}),
    ])
    out = str(tmp_path / "trace.json")
    write_trace([a, b], out)
    trace = json.load(open(out))  # structurally valid JSON by parse
    events = trace["traceEvents"]
    assert isinstance(events, list) and events
    for ev in events:
        assert ev["ph"] in ("M", "X", "C", "i")
        assert isinstance(ev["name"], str)
        assert isinstance(ev["pid"], int)
        if ev["ph"] != "M":
            assert isinstance(ev["ts"], (int, float))
        if ev["ph"] == "X":
            assert ev["dur"] >= 0
    # both processes landed on their meta-declared pid lanes
    pids = {ev["pid"] for ev in events if ev["ph"] != "M"}
    assert pids == {11, 22}
    # spans became duration events with start = end - dur
    fit = [e for e in events if e["ph"] == "X" and e["name"] == "fit"][0]
    assert fit["ts"] == pytest.approx((101.0 - 0.5) * 1e6)
    assert fit["dur"] == pytest.approx(0.5 * 1e6)
    # fault/breaker ride dedicated instant lanes, distinct from spans
    inst = {e["name"]: e for e in events if e["ph"] == "i"}
    assert "fault:put_fail" in inst
    assert any("closed" in n and "open" in n for n in inst)
    assert inst["fault:put_fail"]["tid"] != fit["tid"]


def test_trace_cli_and_obs_trace_env(tmp_path, monkeypatch):
    """SQ_OBS_TRACE renders the closing run's sink automatically."""
    jsonl = str(tmp_path / "run.jsonl")
    trace_path = str(tmp_path / "run.trace.json")
    monkeypatch.setenv("SQ_OBS_TRACE", trace_path)
    obs.enable(jsonl)
    with obs.span("step"):
        pass
    obs.disable()
    trace = json.load(open(trace_path))
    assert any(e.get("name") == "step" for e in trace["traceEvents"])


# -- report ------------------------------------------------------------------


def test_report_self_time_and_sections(capsys, tmp_path):
    from sq_learn_tpu.obs.report import main, render, summarize

    records = [
        _env({"type": "span", "name": "outer", "seq": 1, "dur_s": 1.0,
              "depth": 0, "parent": None, "synced": True, "ts": 101.0}),
        _env({"type": "span", "name": "inner", "seq": 2, "dur_s": 0.75,
              "depth": 1, "parent": 1, "synced": False, "ts": 100.9}),
        _env({"type": "counter", "name": "streaming.transfer_bytes",
              "value": 2048, "delta": 2048, "ts": 100.5}),
        _env({"type": "watchdog", "site": "s.kernel", "compiles": 3,
              "budget": 1, "over_budget": True, "ts": 100.6}),
        _env({"type": "xla_cost", "site": "s.kernel",
              "signature": "(f32[8])", "flops": 1e6,
              "bytes_accessed": 4096.0, "peak_bytes": 8192, "ts": 100.7}),
    ]
    summary = summarize(records)
    # self-time: outer's 1.0s minus inner's 0.75s
    assert summary["spans"]["outer"]["self_s"] == pytest.approx(0.25)
    assert summary["spans"]["inner"]["self_s"] == pytest.approx(0.75)
    assert summary["watchdog"]["s.kernel"]["over_budget"] is True
    assert summary["xla"]["s.kernel"]["flops"] == 1e6
    text = render(summary)
    assert "OVER BUDGET" in text
    assert "streaming.transfer_bytes" in text
    # and the CLI runs end to end on a file
    path = str(tmp_path / "r.jsonl")
    _jsonl(path, records)
    assert main([path]) == 0
    assert "top spans by self-time" in capsys.readouterr().out


# -- regression gate ---------------------------------------------------------


def _bench_line(value=1.0, metric="m", **obs_fields):
    rec = {"metric": metric, "value": value, "unit": "s",
           "vs_baseline": 1.0}
    if obs_fields:
        rec["obs"] = obs_fields
    return rec


class TestRegress:
    def test_green_within_bands(self):
        from sq_learn_tpu.obs.regress import check_record

        history = {"m": [_bench_line(1.0, compile_count=10,
                                     total_transfer_bytes=1 << 20,
                                     peak_hbm_bytes=1 << 24)]}
        verdicts = check_record(
            _bench_line(1.2, compile_count=11,
                        total_transfer_bytes=int(1.1 * (1 << 20)),
                        peak_hbm_bytes=1 << 24), history)
        assert {v["gate"] for v in verdicts} == {
            "latency", "compile_count", "total_transfer_bytes",
            "peak_hbm_bytes"}
        assert all(v["verdict"] == "green" for v in verdicts), verdicts

    def test_forced_retracing_goes_red(self):
        """The acceptance demo: an injected retracing regression
        (compile_count inflated well past the band) turns the verdict
        red while the unmodified run stays green."""
        from sq_learn_tpu.obs.regress import check_record

        history = {"m": [_bench_line(1.0, compile_count=3)]}
        clean = check_record(_bench_line(1.0, compile_count=3), history)
        assert all(v["verdict"] != "red" for v in clean)
        leaked = check_record(_bench_line(1.0, compile_count=40), history)
        red = [v for v in leaked if v["verdict"] == "red"]
        assert [v["gate"] for v in red] == ["compile_count"]

    def test_inflated_transfer_and_latency_go_red(self):
        from sq_learn_tpu.obs.regress import check_record

        history = {"m": [_bench_line(1.0, total_transfer_bytes=1 << 20)]}
        verdicts = check_record(
            _bench_line(5.0, total_transfer_bytes=10 << 20), history)
        by_gate = {v["gate"]: v["verdict"] for v in verdicts}
        assert by_gate["latency"] == "red"
        assert by_gate["total_transfer_bytes"] == "red"

    def test_missing_history_skips_not_passes(self):
        from sq_learn_tpu.obs.regress import check_record

        # pre-obs history: latency comparable, obs gates must SKIP
        history = {"m": [{"metric": "m", "value": 1.0}]}
        verdicts = check_record(_bench_line(1.0, compile_count=999),
                                history)
        by_gate = {v["gate"]: v["verdict"] for v in verdicts}
        assert by_gate["latency"] == "green"
        assert by_gate["compile_count"] == "skip"
        # verdict records are schema-valid obs records
        for v in verdicts:
            assert validate_record(v) == [], v

    def test_check_file_against_repo_history(self, tmp_path):
        from sq_learn_tpu.obs.regress import check_file

        root = tmp_path
        (root / "bench" / "records").mkdir(parents=True)
        (root / "BENCH_r01.json").write_text(json.dumps(
            {"n": 1, "parsed": _bench_line(1.0, compile_count=2)}))
        rec = root / "fresh.txt"
        rec.write_text("# suite run\n"
                       + json.dumps(_bench_line(10.0, compile_count=2))
                       + "\n")
        verdicts = check_file(str(rec), str(root))
        by_gate = {v["gate"]: v["verdict"] for v in verdicts}
        assert by_gate["latency"] == "red"
        assert by_gate["compile_count"] == "green"

    @pytest.mark.slow
    def test_selftest_contract(self):
        from sq_learn_tpu.obs.regress import selftest

        assert selftest() == 0
