"""Pin the driver contract in ``__graft_entry__.py``.

The round-1 multichip gate failed because ``dryrun_multichip`` touched the
default (accelerator) backend before falling back to the CPU mesh — so a
wedged tunnel failed the round artifact. These tests run both entry points
under the conftest (CPU backend, 8 virtual devices) so the contract can
never silently regress again.
"""

import pathlib
import sys

import jax
import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import __graft_entry__  # noqa: E402

# heavyweight tier: deselect with -m 'not slow' (pyproject markers)
pytestmark = pytest.mark.slow


def test_entry_compiles_and_runs():
    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    centers, inertia = out
    assert centers.shape == args[3].shape  # (k, m)
    assert float(inertia) >= 0.0


@pytest.mark.parametrize("n_devices", [1, 2, 8])
def test_dryrun_multichip(n_devices):
    __graft_entry__.dryrun_multichip(n_devices)


def test_dryrun_multichip_never_asks_for_accelerator(monkeypatch):
    """dryrun_multichip must only ever request the CPU backend."""
    real_devices = jax.devices

    def guarded(backend=None):
        assert backend == "cpu", (
            "dryrun_multichip queried a non-CPU backend: "
            f"jax.devices({backend!r})")
        return real_devices(backend)

    monkeypatch.setattr(jax, "devices", guarded)
    __graft_entry__.dryrun_multichip(4)


def test_bench_emits_valid_json_line():
    """The driver records bench.py's stdout as the round's score artifact;
    an import-time or schema breakage must fail the suite, not the round.
    Runs CPU-pinned with the sitecustomize cleared so a wedged accelerator
    relay cannot hang the test."""
    import json
    import os
    import subprocess
    import sys

    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")], cwd=repo, env=env,
        capture_output=True, text=True, timeout=420)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"expected ONE JSON line, got: {lines}"
    rec = json.loads(lines[0])
    for field in ("metric", "value", "unit", "vs_baseline"):
        assert field in rec, rec
    assert rec["unit"] == "s" and rec["value"] > 0
    # Wall-clock on a loaded shared host can legitimately dip below the
    # BASELINE acceptance bar (0.5); that bar is enforced by
    # bench/run_suite.sh on the measurement of record, not here. The unit
    # suite only pins that the ratio is well-formed, warning when low.
    # vs_baseline is null when the sklearn baseline failed to run — the
    # purpose-built assertion on the quality fields below produces the
    # readable failure for that case, so only compare when it's a number
    vb = rec["vs_baseline"]
    assert vb is None or vb > 0, rec
    if vb is not None and vb < 0.5:
        import warnings

        warnings.warn(
            f"bench.py vs_baseline={vb} below the 0.5 "
            "acceptance bar (host load?) — run_suite.sh is the gate")
    # QUALITY floors are load-independent and therefore hard-asserted: a
    # regression that trades clustering accuracy for speed must fail CI.
    # Floor argument: sklearn's own seed-to-seed ARI on digits spans
    # ~0.96-0.98 (local-optimum noise); our median-over-3-seeds measured
    # 0.978-0.983 across CPU and TPU windows of record, while any real
    # quality bug (mis-tuned δ, broken relocation) lands far below 0.9.
    # The floor sits at 0.95 — comfortably under the observed 0.978 low
    # of a seed-dependent statistic (a 0.97 floor was ~0.008 from it,
    # i.e. one unlucky seed/host pairing from a false CI failure; ADVICE
    # r3) yet still far above where any real bug lands.
    # (bench.py emits the quality keys only when its sklearn baseline ran;
    # this environment bundles sklearn, so their absence is itself a bug.)
    ari = rec.get("ari_vs_sklearn_median3")
    inertia = rec.get("inertia_vs_sklearn")
    assert ari is not None and inertia is not None, (
        f"bench.py emitted no quality fields — sklearn baseline path "
        f"failed unexpectedly: {rec}")
    assert ari >= 0.95, rec
    assert abs(inertia - 1.0) <= 0.01, rec
