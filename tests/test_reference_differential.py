"""Differential validation against the REFERENCE implementation itself.

The reference's quantum-routine library (``Utility.py``) is pure
Python/NumPy, so it imports standalone — no Cython build needed. These
tests run the same inputs through the reference's samplers and ours and
compare the *distributions* (deterministic routines compare exactly).
This pins semantic parity directly to the code we are re-designing,
not to a transcription of its formulas.

Skipped wherever the reference checkout is absent.
"""

import importlib.util
import os
import warnings

import numpy as np
import pytest

# heavyweight tier: deselect with -m 'not slow' (pyproject markers)
pytestmark = pytest.mark.slow

REF = "/root/reference/sklearn/QuantumUtility/Utility.py"

if not os.path.exists(REF):  # pragma: no cover
    pytest.skip("reference checkout not available", allow_module_level=True)


@pytest.fixture(scope="module")
def ref():
    spec = importlib.util.spec_from_file_location("ref_utility", REF)
    mod = importlib.util.module_from_spec(spec)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # reference has SyntaxWarning etc.
        spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def key():
    import jax

    return jax.random.PRNGKey(0)


@pytest.fixture(scope="module")
def ref_qsvm(ref):
    """The reference's ``_qSVM.py``, loaded with a synthetic package that
    aliases its fork-relative imports to stock sklearn (the fork is an
    unbuilt sklearn tree; only these leaf modules are needed). Reuses the
    already-loaded ``ref`` Utility module as refpkg.QuantumUtility."""
    import sys
    import types

    import sklearn.base
    import sklearn.metrics
    import sklearn.metrics.pairwise
    import sklearn.utils.validation

    qutil = ref

    class _CompatBase(sklearn.base.BaseEstimator):
        # sklearn ≥1.6 dropped _validate_data; the fork (1.0.dev) had it
        def _validate_data(self, X, y=None, **kw):
            import sklearn.utils.validation as v

            if y is None:
                return v.check_array(X, **kw)
            return v.check_X_y(X, y, **kw)

    pkg = types.ModuleType("refpkg"); pkg.__path__ = []
    svm = types.ModuleType("refpkg.svm"); svm.__path__ = []
    base = types.ModuleType("refpkg.svm._base")
    base.BaseEstimator = _CompatBase
    utils = types.ModuleType("refpkg.utils"); utils.__path__ = []
    mods = {
        "refpkg": pkg,
        "refpkg.svm": svm,
        "refpkg.svm._base": base,
        "refpkg.utils": utils,
        "refpkg.utils.validation": sklearn.utils.validation,
        "refpkg.metrics": sklearn.metrics,
        "refpkg.metrics.pairwise": sklearn.metrics.pairwise,
        "refpkg.QuantumUtility": qutil,
    }
    saved = {k: sys.modules.get(k) for k in mods}
    sys.modules.update(mods)
    try:
        spec = importlib.util.spec_from_file_location(
            "refpkg.svm._qSVM", "/root/reference/sklearn/svm/_qSVM.py")
        mod = importlib.util.module_from_spec(spec)
        sys.modules["refpkg.svm._qSVM"] = mod
        spec.loader.exec_module(mod)
        yield mod
    finally:
        for k, v in saved.items():
            if v is None:
                sys.modules.pop(k, None)
            else:
                sys.modules[k] = v
        sys.modules.pop("refpkg.svm._qSVM", None)


def test_qlssvc_classical_solve_parity(ref_qsvm):
    from sq_learn_tpu.models import QLSSVC

    rng = np.random.default_rng(0)
    n = 60
    X = rng.normal(size=(n, 6))
    y = np.sign(X[:, 0] + 0.5 * X[:, 1] + 0.1 * rng.normal(size=n))
    # low-rank truncation parity only on kernels with distinct spectra:
    # the linear kernel with n ≫ d makes F's eigenvalue 1/γ degenerate
    # with multiplicity ~n−d, and truncating inside that eigenspace is
    # basis-arbitrary (the reference's own output is LAPACK-arbitrary)
    cases = [("linear", False, 0.9), ("rbf", False, 0.9),
             ("rbf", True, 0.95), ("poly", False, 0.9),
             ("poly", True, 0.95)]
    for kernel, low_rank, var in cases:
        r = ref_qsvm.QLSSVC(kernel=kernel, penalty=0.1, low_rank=low_rank,
                            var=var)
        r.fit(X, y)
        o = QLSSVC(kernel=kernel, penalty=0.1, low_rank=low_rank, var=var,
                   random_state=0).fit(X, y)
        # our solve runs in float32; truncated pseudo-inverses amplify
        # the precision gap by the retained condition number
        atol = 5e-4 if low_rank else 1e-5
        np.testing.assert_allclose(o.b_, r.b, rtol=1e-3, atol=atol)
        np.testing.assert_allclose(o.alpha_, r.alpha, rtol=1e-2, atol=atol)
        np.testing.assert_allclose(o.cond_, r.cond, rtol=1e-3)
        np.testing.assert_allclose(np.asarray(o.get_h(X)), r.get_h(X),
                                   rtol=1e-2, atol=1e-3)


def _tv_distance(a, b, bins):
    """Total-variation distance between two empirical samples on shared
    bins."""
    pa, _ = np.histogram(a, bins=bins)
    pb, _ = np.histogram(b, bins=bins)
    pa = pa / pa.sum()
    pb = pb / pb.sum()
    return 0.5 * np.abs(pa - pb).sum()


def test_best_mu_exact_parity(ref):
    from sq_learn_tpu.ops.quantum.norms import best_mu, linear_search

    rng = np.random.default_rng(0)
    A = rng.normal(size=(60, 17))
    # same grid as the reference default (step=0.05)
    p_ref, v_ref = ref.linear_search(A, 0.0, 1.0, 0.05)
    p_ours, v_ours = linear_search(A, 0.0, 1.0, 0.05)
    assert p_ours == pytest.approx(p_ref, abs=1e-9)
    assert v_ours == pytest.approx(v_ref, rel=1e-5)
    name_ref, val_ref = ref.best_mu(A)
    name_ours, val_ours = best_mu(A)
    assert val_ours == pytest.approx(val_ref, rel=1e-5)
    # winner side agrees (mu grid vs Frobenius)
    assert ("frobenius" in str(name_ours).lower()) == \
        ("frobenius" in str(name_ref).lower())


def test_amplitude_estimation_distribution(ref, key):
    from sq_learn_tpu.ops.quantum import amplitude_estimation

    a, eps, n = 0.3, 0.05, 4000
    ref_draws = np.array([ref.amplitude_estimation(a, epsilon=eps)
                          for _ in range(n)])
    ours = np.asarray(amplitude_estimation(key, np.full(n, a), epsilon=eps))
    bins = np.linspace(0.0, 1.0, 60)
    tv = _tv_distance(ref_draws, ours, bins)
    assert tv < 0.08, tv
    # both concentrate within eps of the true amplitude
    assert np.mean(np.abs(ref_draws - a) <= eps) > 0.8
    assert np.mean(np.abs(ours - a) <= eps) > 0.8


def test_phase_estimation_distribution(ref, key):
    from sq_learn_tpu.ops.quantum import phase_estimation

    omega, eps, gamma, n = 0.37, 0.05, 0.1, 4000
    ref_draws = np.array([ref.phase_estimation(omega, epsilon=eps,
                                               gamma=gamma)
                          for _ in range(n)])
    ours = np.asarray(phase_estimation(key, np.full(n, omega), epsilon=eps,
                                       gamma=gamma))
    bins = np.linspace(0.0, 1.0, 80)
    tv = _tv_distance(ref_draws, ours, bins)
    assert tv < 0.08, tv
    assert np.mean(np.abs(ref_draws - omega) <= eps) > 0.9
    assert np.mean(np.abs(ours - omega) <= eps) > 0.9


def test_tomography_error_distribution(ref, key):
    import jax

    from sq_learn_tpu.ops.quantum import real_tomography

    rng = np.random.default_rng(1)
    # delta sizes the reference's materialized draw count (N = 36·d·lnd/δ²
    # per rep, built with Python Counter overhead) — 0.45/16 keeps this
    # test ~15 s instead of ~75 s with the same error-scale comparison
    d, delta, reps = 32, 0.45, 16
    v = rng.normal(size=d)
    v /= np.linalg.norm(v)
    ref_errs = []
    for _ in range(reps):
        # the reference returns {N: estimate} (Utility.py:402)
        out = ref.real_tomography(v.copy(), delta=delta,
                                  incremental_measure=False)
        est = np.asarray(list(out.values())[-1])
        ref_errs.append(np.linalg.norm(est - v))
    our_errs = []
    for k in jax.random.split(key, reps):
        est = np.asarray(real_tomography(k, v, delta=delta))
        our_errs.append(np.linalg.norm(est - v))
    ref_errs, our_errs = np.array(ref_errs), np.array(our_errs)
    # same error scale (means within 50% of each other) and both ≤ δ
    assert np.all(ref_errs <= delta) and np.all(our_errs <= delta)
    assert np.mean(our_errs) == pytest.approx(np.mean(ref_errs), rel=0.5)


def test_gaussian_estimate_noise_scale(ref, key):
    from sq_learn_tpu.ops.quantum import gaussian_estimate

    rng = np.random.default_rng(2)
    d, noise = 256, 0.1
    v = rng.normal(size=d)
    ref_err = ref.make_gaussian_est(v.copy(), noise) - v
    our_err = np.asarray(gaussian_estimate(key, v, noise)) - v
    # truncnorm(±noise/sqrt(d)) per component on both sides
    assert np.std(our_err) == pytest.approx(np.std(ref_err), rel=0.35)
    bound = noise / np.sqrt(d) + 1e-9
    assert np.all(np.abs(ref_err) <= bound)
    assert np.all(np.abs(our_err) <= bound)


def test_consistent_phase_estimation_agreement(ref, key):
    import jax

    from sq_learn_tpu.ops.quantum import consistent_phase_estimation

    omega, eps, gamma = 0.42, 0.05, 0.1
    ref_outs = {round(float(ref.consistent_phase_estimation(
        epsilon=eps, gamma=gamma, omega=omega)), 10) for _ in range(40)}
    our_outs = {round(float(consistent_phase_estimation(
        k, omega, eps, gamma)), 10)
        for k in jax.random.split(key, 40)}
    # CPE's point: repeated calls agree almost always — each side is
    # (near-)constant and the modal outputs are within one eps-interval
    assert len(ref_outs) <= 2 and len(our_outs) <= 2
    assert abs(min(our_outs) - min(ref_outs)) <= eps


def test_matrix_gaussian_tomography_flattening(ref, key):
    # the reference flattens a matrix before the Gaussian path
    # (Utility.py:159-166), so the truncnorm bound uses d = n·m, not the
    # row width — pinned on both sides
    from sq_learn_tpu.ops.quantum import tomography

    rng = np.random.default_rng(4)
    A = rng.normal(size=(12, 30))
    noise = 0.2
    ref_err = ref.tomography(A.copy(), noise, true_tomography=False) - A
    our_err = np.asarray(tomography(key, A, noise,
                                    true_tomography=False)) - A
    bound = noise / np.sqrt(A.size) + 1e-9
    assert np.all(np.abs(ref_err) <= bound)
    assert np.all(np.abs(our_err) <= bound)
    assert np.std(our_err) == pytest.approx(np.std(ref_err), rel=0.35)


def test_amplitude_estimation_median_boost(ref, key):
    from sq_learn_tpu.ops.quantum import amplitude_estimation

    a, eps, gamma, n = 0.25, 0.1, 0.05, 400
    ref_draws = np.array([ref.amplitude_estimation(a, epsilon=eps,
                                                   gamma=gamma)
                          for _ in range(n)])
    ours = np.asarray(amplitude_estimation(key, np.full(n, a), epsilon=eps,
                                           gamma=gamma))
    # median boosting tightens both to within eps almost surely
    assert np.mean(np.abs(ref_draws - a) <= eps) > 0.97
    assert np.mean(np.abs(ours - a) <= eps) > 0.97
    assert np.mean(ours) == pytest.approx(np.mean(ref_draws), abs=eps / 2)


def test_quantum_state_measure_distribution(ref, key):
    from sq_learn_tpu.ops.quantum import QuantumState

    amps = np.array([0.5, -0.5, 0.5, 0.5])
    regs = np.arange(4)
    ref_counts = np.bincount(
        ref.QuantumState(registers=regs, amplitudes=amps).measure(8000),
        minlength=4)
    ours = QuantumState(registers=regs, amplitudes=amps)
    our_draws = np.asarray(ours.measure(key, 8000))
    our_counts = np.bincount(our_draws, minlength=4)
    # both sample registers w.p. amplitude² = 1/4 each
    for c in (ref_counts, our_counts):
        assert np.all(np.abs(c / 8000 - 0.25) < 0.03), c


def test_ipe_distribution(ref, key):
    import jax

    from sq_learn_tpu.ops.quantum import ipe

    rng = np.random.default_rng(3)
    x, y = rng.normal(size=8), rng.normal(size=8)
    eps, n = 0.1, 300
    true_ip = float(x @ y)
    ref_draws = np.array([ref.ipe(x, y, eps, Q=1, gamma=0.1)
                          for _ in range(n)])
    keys = jax.random.split(key, n)
    our_draws = np.array([float(ipe(k, x @ x, y @ y, true_ip, epsilon=eps,
                                    Q=1))
                          for k in keys[:n]])
    tol = eps * max(1.0, abs(true_ip))
    assert np.mean(np.abs(ref_draws - true_ip) <= tol) > 0.7
    assert np.mean(np.abs(our_draws - true_ip) <= tol) > 0.7
    assert np.mean(our_draws) == pytest.approx(np.mean(ref_draws),
                                               abs=2 * tol)


def test_estimate_wald_exact_parity(ref, key):
    """Deterministic given the same draws: the reference's Counter-based
    frequency dict and our counts-based estimator must agree up to
    float32 rounding (``Utility.py:61-64``)."""
    from sq_learn_tpu.ops.quantum import QuantumState
    from sq_learn_tpu.ops.quantum.sampling import estimate_wald

    amps = np.array([0.8, 0.4, 0.4, 0.2])
    amps = amps / np.linalg.norm(amps)
    regs = np.arange(4)
    draws = np.asarray(
        QuantumState(registers=regs, amplitudes=amps).measure(key, 5000))
    ref_freq = ref.estimate_wald(list(draws))
    counts = np.bincount(draws, minlength=4)
    ours = np.asarray(estimate_wald(counts, len(draws)))
    # abs=1e-6: our estimator returns float32 (x64 off under the test
    # conftest), so parity is exact up to f32 rounding of count/n —
    # ~2e-8 worst-case here, not the f64-exactness a tighter bound
    # would falsely claim
    for reg in regs:
        assert ours[reg] == pytest.approx(ref_freq.get(reg, 0.0), abs=1e-6)


def test_coupon_collect_distribution(ref, key):
    """Both implementations draw until every basis state is seen; the
    mean draw count over repeats must match (and match the analytic
    harmonic-number expectation for the uniform case, n·H_n ≈ 8.33 for
    d=4) — reference ``Utility.py:75-85`` vs our lax.while_loop form."""
    import jax

    from sq_learn_tpu.ops.quantum import QuantumState
    from sq_learn_tpu.ops.quantum.state import coupon_collect

    amps = np.full(4, 0.5)
    regs = np.arange(4)
    reps = 300
    ref_state = ref.QuantumState(registers=regs, amplitudes=amps)
    ref_counts = [ref.coupon_collect(ref_state) for _ in range(reps)]
    ours_state = QuantumState(registers=regs, amplitudes=amps)
    keys = jax.random.split(key, reps)
    our_counts = [int(coupon_collect(k, ours_state)) for k in keys]
    expected = 4 * (1 + 1 / 2 + 1 / 3 + 1 / 4)  # n·H_n = 8.33
    assert np.mean(ref_counts) == pytest.approx(expected, rel=0.15)
    assert np.mean(our_counts) == pytest.approx(expected, rel=0.15)
    assert np.mean(our_counts) == pytest.approx(np.mean(ref_counts),
                                                rel=0.2)
