"""Two real processes over a localhost coordinator (VERDICT round 1 weak #8):
``distributed.initialize()`` + ``global_mesh()`` + cross-process collectives
actually run, not just the shard-bounds arithmetic. Uses JAX's multi-process
CPU support — each worker brings 2 virtual devices into a 4-device global
runtime.
"""

import os
import pathlib
import socket
import subprocess
import sys

import pytest

# heavyweight tier: deselect with -m 'not slow' (pyproject markers)
pytestmark = pytest.mark.slow

REPO = pathlib.Path(__file__).resolve().parent.parent
WORKER = REPO / "tests" / "_dist_worker.py"


def _free_port():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]



def test_two_process_initialize_mesh_and_psum():
    port = _free_port()
    env = dict(os.environ)
    env.pop("PYTHONSTARTUP", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = str(REPO)

    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(pid), "2", str(port),
             str(REPO)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("distributed workers timed out:\n" + "\n".join(outs))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"worker {pid} OK" in out
