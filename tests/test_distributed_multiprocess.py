"""Two real processes over a localhost coordinator (VERDICT round 1 weak #8):
``distributed.initialize()`` + ``global_mesh()`` + cross-process collectives
actually run, not just the shard-bounds arithmetic. Uses JAX's multi-process
CPU support — each worker brings 2 virtual devices into a 4-device global
runtime.
"""

import os
import pathlib
import socket
import subprocess
import sys

import pytest

# heavyweight tier: deselect with -m 'not slow' (pyproject markers)
pytestmark = pytest.mark.slow

REPO = pathlib.Path(__file__).resolve().parent.parent
WORKER = REPO / "tests" / "_dist_worker.py"
ELASTIC_WORKER = REPO / "tests" / "_elastic_worker.py"


def _free_port():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _worker_env():
    env = dict(os.environ)
    env.pop("PYTHONSTARTUP", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = str(REPO)
    return env


def _run_elastic_workers(mode, ports, n=2, timeout=240):
    procs = [
        subprocess.Popen(
            [sys.executable, str(ELASTIC_WORKER), mode, str(pid)]
            + [str(p) for p in ports] + [str(REPO)],
            env=_worker_env(), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        for pid in range(n)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("elastic workers timed out:\n" + "\n".join(outs))
    return procs, outs



def test_two_process_initialize_mesh_and_psum():
    port = _free_port()
    env = dict(os.environ)
    env.pop("PYTHONSTARTUP", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = str(REPO)

    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(pid), "2", str(port),
             str(REPO)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("distributed workers timed out:\n" + "\n".join(outs))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"worker {pid} OK" in out


def test_elastic_shutdown_and_reinit_next_generation():
    """ISSUE 18 satellite: the raw-client elastic path tears a world
    down and re-forms the next generation IN THE SAME PROCESSES — join
    g0 (service hosted here, outside the mesh), prove same-generation
    re-init is a no-op and a different generation while live raises,
    psum, shutdown, join g1 on a fresh service, psum again. ISSUE 19
    rides along inside the worker: worker 1 joins without a fleet
    run_id and must adopt worker 0's through the world's KV store,
    both re-stamp the generation at every join, and the fsync'd shard
    carries the envelope on disk before ``os._exit``."""
    from sq_learn_tpu.parallel import distributed as dist

    p0, p1 = _free_port(), _free_port()
    services = [dist.start_coordinator_service(f"localhost:{p0}", 2),
                dist.start_coordinator_service(f"localhost:{p1}", 2)]
    try:
        procs, outs = _run_elastic_workers("reinit", [p0, p1])
        for pid, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"worker {pid} failed:\n{out}"
            assert f"worker {pid} REINIT OK" in out
    finally:
        del services  # after every client is gone (workers exited)


def test_elastic_mixed_generation_join_refused():
    """Two workers carry generations 0 and 1 to one service: whichever
    publishes first wins the handshake, the other must get
    GenerationMismatchError — a refusal, never a gloo hang."""
    from sq_learn_tpu.parallel import distributed as dist

    port = _free_port()
    services = [dist.start_coordinator_service(f"localhost:{port}", 2)]
    try:
        procs, outs = _run_elastic_workers("mismatch", [port])
        for pid, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        verdicts = sorted(line.split()[-1] for out in outs
                          for line in out.splitlines()
                          if line.startswith("worker "))
        assert verdicts == ["JOINED", "MISMATCH"], (verdicts, outs)
    finally:
        del services
