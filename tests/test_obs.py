"""Observability layer (sq_learn_tpu.obs): recorder, ledger, watchdog,
probe, schema — the run-scoped metrics/tracing contract of ISSUE 2."""

import json
import time

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sq_learn_tpu import obs
from sq_learn_tpu.obs.ledger import tomography_shot_count
from sq_learn_tpu.obs.schema import validate_jsonl, validate_record


@pytest.fixture
def run():
    """A fresh in-memory observability run, torn down afterwards."""
    rec = obs.enable()
    yield rec
    obs.disable()


# -- disabled fast path ------------------------------------------------------


def test_disabled_span_is_shared_noop():
    obs.disable()
    assert obs.span("anything", big=1) is obs.NULL_SPAN
    with obs.span("x") as sp:
        assert sp.set(a=1) is sp
        assert sp.sync("v") == "v"
    assert obs.snapshot() is None
    assert obs.ledger.entries() == []


def test_disabled_overhead_micro():
    """The disabled instrumentation points must be cheap enough to leave
    in every hot path: ~1 µs/op would already be 100× slower than the
    observed cost, so the bound below is loose against host noise while
    still catching an accidental allocation/format on the fast path."""
    obs.disable()
    n = 100_000
    t0 = time.perf_counter()
    for _ in range(n):
        with obs.span("hot", a=1):
            pass
        obs.counter_add("c", 1)
        obs.gauge("g", 1.0)
    elapsed = time.perf_counter() - t0
    assert elapsed < 2.0, f"disabled-mode overhead too high: {elapsed:.3f}s"


# -- spans -------------------------------------------------------------------


def test_span_nesting_and_ordering(run):
    with obs.span("outer", stage="fit") as sp_out:
        with obs.span("inner"):
            pass
        sp_out.set(resolved="full")
    # children close (and record) before parents
    assert [s["name"] for s in run.spans] == ["inner", "outer"]
    inner, outer = run.spans
    assert inner["depth"] == 1 and outer["depth"] == 0
    assert inner["parent"] == outer["seq"]
    assert outer["parent"] is None
    assert inner["seq"] > outer["seq"]  # opened after
    assert outer["attrs"] == {"stage": "fit", "resolved": "full"}
    assert not inner["synced"]


def test_span_sync_blocks_and_flags(run):
    with obs.span("synced") as sp:
        out = sp.sync(jnp.ones((4,)) * 2)
    assert run.spans[0]["synced"] is True
    np.testing.assert_allclose(np.asarray(out), 2.0)


def test_span_records_error(run):
    with pytest.raises(ValueError):
        with obs.span("boom"):
            raise ValueError("x")
    assert run.spans[0]["error"] == "ValueError"


# -- counters / gauges / snapshot -------------------------------------------


def test_counters_accumulate_and_gauges_overwrite(run):
    obs.counter_add("bytes", 10)
    obs.counter_add("bytes", 5)
    obs.gauge("latency", 0.5)
    obs.gauge("latency", 0.7, source="probe")
    assert run.counters["bytes"] == 15
    assert run.gauges["latency"] == 0.7


def test_snapshot_fields(run):
    snap = obs.snapshot()
    for key in ("compile_count", "total_transfer_bytes", "probe_ms",
                "spans", "ledger_entries", "watchdog_over_budget"):
        assert key in snap
    assert snap["probe_ms"] is None
    obs.probe.probe_device(platform="cpu")
    assert run.probe_events[-1]["outcome"] == "cpu"
    assert obs.snapshot()["probe_ms"] is not None


# -- JSONL sink + schema -----------------------------------------------------


def test_jsonl_schema_roundtrip(tmp_path):
    path = str(tmp_path / "run.jsonl")
    obs.enable(path)
    try:
        with obs.span("step", n=3):
            pass
        obs.counter_add("streaming.transfer_bytes", 128)
        obs.gauge("probe.latency_s", 0.01)
        obs.ledger.record("qpca", "tomography",
                          queries={"tomography_shots": 42.0},
                          budget={"delta": 0.1}, wall_s=0.5)
        f = jax.jit(lambda x: x + 1)
        obs.watchdog.track("t.roundtrip", f, budget=2)
        f(jnp.ones((3,)))
        obs.watchdog.observe("t.roundtrip")
        obs.probe.probe_device(platform="cpu")
    finally:
        obs.disable()
    summary = validate_jsonl(path)
    assert summary["errors"] == []
    for t in ("meta", "span", "counter", "gauge", "ledger", "watchdog",
              "probe"):
        assert summary["by_type"].get(t, 0) >= 1, (t, summary)
    # and the lines decode back to the recorded values
    recs = [json.loads(l) for l in open(path)]
    led = [r for r in recs if r["type"] == "ledger"][0]
    assert led["queries"]["tomography_shots"] == 42.0
    assert led["budget"]["delta"] == 0.1


def test_schema_rejects_malformed():
    assert validate_record({"v": 1, "ts": 0.0, "type": "nope"})
    assert validate_record({"v": 1, "ts": 0.0, "type": "span",
                            "name": 3, "seq": "x", "dur_s": -1,
                            "depth": 0, "parent": None, "synced": True})
    assert validate_record({"v": 99, "ts": 0.0, "type": "gauge",
                            "name": "g", "value": 1})


# -- retracing watchdog ------------------------------------------------------


def test_watchdog_fires_on_retracing_callable(run):
    f = jax.jit(lambda x: x * 2)
    obs.watchdog.track("t.retrace", f, budget=1)
    f(jnp.ones((4,)))
    assert obs.watchdog.observe("t.retrace") == 1  # within budget
    f(jnp.ones((5,)))  # new shape -> recompile -> over budget
    with pytest.warns(obs.RetracingWarning, match="t.retrace"):
        assert obs.watchdog.observe("t.retrace") == 2
    assert obs.watchdog.report()["t.retrace"]["over_budget"]
    # the violation also landed as a watchdog record
    assert any(e["over_budget"] for e in run.watchdog_events)


def test_watchdog_strict_raises(run, monkeypatch):
    monkeypatch.setenv("SQ_OBS_STRICT", "1")
    f = jax.jit(lambda x: x - 1)
    wrapped = obs.watchdog.watch("t.strict", f, budget=1)
    wrapped(jnp.ones((4,)))
    with pytest.raises(obs.RetracingError, match="t.strict"):
        wrapped(jnp.ones((6,)))


def test_watchdog_signature_budget_and_baseline(run):
    f = jax.jit(lambda x: jnp.sum(x))
    f(jnp.ones((3,)))  # compiled BEFORE tracking: baselined away
    obs.watchdog.track("t.base", f)
    obs.watchdog.allow("t.base", (4, "float32"))
    obs.watchdog.allow("t.base", (8, "float32"))
    f(jnp.ones((4,)))
    f(jnp.ones((8,)))
    assert obs.watchdog.observe("t.base") == 2  # == len(signatures): ok
    assert not obs.watchdog.report()["t.base"]["over_budget"]


# -- streaming instrumentation ----------------------------------------------


def test_streaming_counters_and_watchdog(run):
    from sq_learn_tpu import streaming

    X = np.random.default_rng(0).normal(size=(512, 16)).astype(np.float32)
    streaming.streamed_centered_gram(X, max_bytes=8 * 1024)
    assert run.counters["streaming.transfer_bytes"] >= X.nbytes
    assert run.counters["streaming.tiles"] >= 2
    rep = obs.watchdog.report()["streaming.gram_colsum"]
    assert rep["observations"] == 1
    assert not rep["over_budget"]
    # a second pass at another size re-observes without minting compiles
    # beyond the allowed buckets
    streaming.streamed_centered_gram(X[:300], max_bytes=8 * 1024)
    rep = obs.watchdog.report()["streaming.gram_colsum"]
    assert rep["compiles"] <= rep["budget"]


# -- quantum-runtime ledger --------------------------------------------------


def test_ledger_matches_hand_computed_tomography_shots(run):
    from sq_learn_tpu.models import QPCA

    X = np.random.default_rng(1).normal(size=(256, 32)).astype(np.float32)
    est = QPCA(n_components=8, svd_solver="full", random_state=0)
    # eps=0: exact singular-value estimates, so the top-k selection (and
    # therefore the shot count) is deterministic; delta>0 prices tomography
    est.fit(X, estimate_all=True, theta_major=1.0, eps=0, delta=0.3,
            true_tomography=False)
    k = est.topk
    assert k > 0
    # Alg. 4.1: 2·N(d)·k shots per side — right vectors live in R^32,
    # left in R^256
    expected = (tomography_shot_count(k, 32, 0.3)
                + tomography_shot_count(k, 256, 0.3))
    totals = obs.ledger.totals()
    assert totals["queries"]["tomography_shots"] == expected
    assert totals["queries"]["pe_spectrum_queries"] == 0  # eps=0 exact
    assert totals["wall_s"] > 0


def test_ledger_zero_error_records_zero_queries(run):
    from sq_learn_tpu.models import QPCA

    X = np.random.default_rng(2).normal(size=(128, 16)).astype(np.float32)
    est = QPCA(n_components=4, svd_solver="full", random_state=0)
    est.fit(X, estimate_all=True, theta_major=1.0, eps=0, delta=0,
            spectral_norm_est=True)
    totals = obs.ledger.totals()
    assert all(v == 0 for v in totals["queries"].values()), totals
    steps = {(e["estimator"], e["step"]) for e in obs.ledger.entries()}
    assert ("qpca", "topk_extract") in steps
    assert ("qpca", "spectral_norm_estimation") in steps


def test_ledger_qkmeans_quantum_cost(run):
    from sq_learn_tpu.models import QKMeans

    X = np.random.default_rng(3).normal(size=(128, 8)).astype(np.float32)
    QKMeans(n_clusters=3, delta=0.4, true_distance_estimate=False,
            n_init=1, max_iter=5, random_state=0).fit(X)
    entry = [e for e in obs.ledger.entries()
             if (e["estimator"], e["step"]) == ("qkmeans", "fit")][0]
    assert entry["queries"]["theoretical_quantum_cost"] > 0
    assert entry["budget"]["delta"] == 0.4


def test_ledger_classical_estimators_feed_wall_clock(run):
    from sq_learn_tpu.models import KNeighborsClassifier, TruncatedSVD

    X = np.random.default_rng(4).normal(size=(64, 8)).astype(np.float32)
    TruncatedSVD(n_components=2, random_state=0).fit(X)
    KNeighborsClassifier(n_neighbors=3).fit(
        X, np.arange(64) % 2).predict(X[:5])
    steps = {(e["estimator"], e["step"]): e for e in obs.ledger.entries()}
    assert steps[("truncated_svd", "fit")]["queries"] == {}
    assert steps[("truncated_svd", "fit")]["wall_s"] >= 0
    assert steps[("knn", "search")]["queries"] == {}


# -- profiling refactor ------------------------------------------------------


def test_timer_emits_span(run):
    from sq_learn_tpu.utils.profiling import Timer

    with Timer(name="unit.timer") as t:
        jnp.ones((8,)).block_until_ready()
    assert t.elapsed is not None
    assert any(s["name"] == "unit.timer" for s in run.spans)


def test_benchmark_records_compile_execute_split(run):
    from sq_learn_tpu.utils.profiling import benchmark

    f = jax.jit(lambda x: x * 3)
    median, times = benchmark(f, jnp.ones((16,)), repeats=3, warmup=1,
                              name="triple")
    assert len(times) == 3 and median >= 0
    assert "benchmark.triple.warmup_s" in run.gauges
    assert "benchmark.triple.median_s" in run.gauges


def test_mfu_finite_on_cpu_backend(run, monkeypatch):
    """The CPU backend prices MFU against the host-CPU peak estimate —
    a finite float tagged cpu_estimate, instead of the pre-v2 None +
    unknown_chip gauge that left bench_pallas_mfu blind off-TPU."""
    from sq_learn_tpu.utils import profiling

    monkeypatch.delenv("SQ_TPU_PEAK_FLOPS", raising=False)
    value = profiling.mfu(1e9, 1.0)
    assert isinstance(value, float) and np.isfinite(value) and value > 0
    recs = [r for r in run.gauge_events if r["name"] == "profiling.mfu"]
    assert recs, "no mfu gauge recorded"
    assert recs[-1]["attrs"]["cpu_estimate"] is True


def test_mfu_degrades_gracefully_on_unknown_accelerator(run, monkeypatch):
    from sq_learn_tpu.utils import profiling

    monkeypatch.delenv("SQ_TPU_PEAK_FLOPS", raising=False)

    class UnknownChip:  # an accelerator the peak table doesn't know
        device_kind = "TPU v99"
        platform = "axon"

    assert profiling.mfu(1e12, 1.0, device=UnknownChip()) is None
    recs = [r for r in run.gauge_events if r["name"] == "profiling.mfu"]
    assert recs, "no mfu gauge recorded"
    assert recs[-1]["attrs"]["unknown_chip"] is True
    assert recs[-1]["attrs"]["reason"] == "unknown_chip"


# -- probe -------------------------------------------------------------------


def test_probe_cpu_and_skipped_paths(run):
    out = obs.probe.probe_device(platform="cpu")
    assert out["outcome"] == "cpu" and out["latency_s"] == 0.0
    out = obs.probe.probe_device(platform="")
    assert out["outcome"] == "skipped"
    assert len(run.probe_events) == 2
    assert run.gauges["probe.ok"] is True
