"""qPCA tests: classical parity vs sklearn PCA, quantum estimator error
bounds, transform/inverse round trips (SURVEY §4 test plan items 1-3)."""

import numpy as np
import pytest
import sklearn.datasets
import sklearn.decomposition

from sq_learn_tpu import clone
from sq_learn_tpu.models import PCA, QPCA
from sq_learn_tpu.models.qpca import _infer_dimension, singular_value_estimates


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(42)
    # low-rank-ish data with decaying spectrum
    B = rng.normal(size=(200, 20)) @ rng.normal(size=(20, 30))
    X = B + 0.05 * rng.normal(size=(200, 30))
    return X.astype(np.float64)


@pytest.fixture(scope="module")
def digits():
    X, _ = sklearn.datasets.load_digits(return_X_y=True)
    return X.astype(np.float64)


class TestClassicalParity:
    def test_matches_sklearn_full(self, data):
        ours = PCA(n_components=5, random_state=0).fit(data)
        ref = sklearn.decomposition.PCA(
            n_components=5, svd_solver="full").fit(data)
        # compute happens in float32 on device — tolerances reflect that
        np.testing.assert_allclose(
            ours.explained_variance_, ref.explained_variance_, rtol=1e-4)
        np.testing.assert_allclose(
            ours.singular_values_, ref.singular_values_, rtol=1e-4)
        np.testing.assert_allclose(
            np.abs(ours.components_), np.abs(ref.components_), atol=1e-3)
        np.testing.assert_allclose(ours.mean_, ref.mean_, rtol=1e-4,
                                   atol=1e-6)
        np.testing.assert_allclose(
            ours.noise_variance_, ref.noise_variance_, rtol=1e-3)

    def test_transform_matches_sklearn(self, data):
        ours = PCA(n_components=4).fit(data)
        ref = sklearn.decomposition.PCA(
            n_components=4, svd_solver="full").fit(data)
        # our flip is the deterministic V-based convention (svd_flip_v),
        # which can differ per-component from sklearn's u-based one
        # (extmath.py:522); installed sklearn may use a different basis —
        # align per-column signs before comparing
        A, B = ours.transform(data), ref.transform(data)
        signs = np.sign(np.sum(A * B, axis=0))
        np.testing.assert_allclose(A * signs, B, rtol=1e-3, atol=1e-4)

    def test_inverse_transform_round_trip(self, data):
        pca = PCA(n_components=20).fit(data)
        Xr = pca.inverse_transform(pca.transform(data))
        # rank ~20 signal: reconstruction error limited to the noise floor
        rel = np.linalg.norm(data - Xr) / np.linalg.norm(data)
        assert rel < 0.05

    def test_whiten(self, data):
        pca = PCA(n_components=5, whiten=True).fit(data)
        Xt = pca.transform(data)
        np.testing.assert_allclose(np.var(Xt, axis=0, ddof=1),
                                   np.ones(5), rtol=1e-3)
        Xr = pca.inverse_transform(Xt)
        ref = sklearn.decomposition.PCA(
            n_components=5, whiten=True, svd_solver="full").fit(data)
        np.testing.assert_allclose(Xr, ref.inverse_transform(ref.transform(data)),
                                   rtol=1e-3, atol=1e-4)

    def test_fractional_n_components(self, data):
        ours = PCA(n_components=0.9).fit(data)
        ref = sklearn.decomposition.PCA(
            n_components=0.9, svd_solver="full").fit(data)
        assert ours.n_components_ == ref.n_components_

    def test_mle_matches_sklearn(self, data):
        ours = PCA(n_components="mle").fit(data)
        ref = sklearn.decomposition.PCA(
            n_components="mle", svd_solver="full").fit(data)
        assert ours.n_components_ == ref.n_components_

    def test_infer_dimension_matches_sklearn_internal(self, data):
        from sklearn.decomposition._pca import (
            _infer_dimension as sk_infer,
        )

        X = data - data.mean(axis=0)
        S = np.linalg.svd(X, compute_uv=False)
        spectrum = S**2 / (len(X) - 1)
        assert _infer_dimension(spectrum, len(X)) == sk_infer(spectrum, len(X))

    def test_randomized_solver_close(self, data):
        with pytest.warns(UserWarning, match="purely classic"):
            ours = QPCA(n_components=5, svd_solver="randomized",
                        random_state=0).fit(data)
        ref = sklearn.decomposition.PCA(
            n_components=5, svd_solver="full").fit(data)
        np.testing.assert_allclose(
            ours.explained_variance_, ref.explained_variance_, rtol=1e-2)

    def test_auto_dispatch(self, data):
        small = QPCA(n_components=5).fit(data)  # max dim 200 ≤ 500 → full
        assert small._fit_svd_solver == "full"

    def test_clone(self, data):
        est = QPCA(n_components=3, whiten=True, random_state=1)
        c = clone(est)
        assert c.get_params() == est.get_params()

    def test_fit_transform_works(self, data):
        # the reference's fit_transform crashes on stale kwargs
        # (_qPCA.py:467-473); ours is standard fit-then-transform
        pca = PCA(n_components=3)
        Xt = pca.fit_transform(data)
        np.testing.assert_allclose(Xt, pca.transform(data), rtol=1e-5,
                                   atol=1e-6)


class TestQuantumEstimators:
    def test_sv_estimates_within_eps(self, key):
        rng = np.random.default_rng(0)
        S = np.sort(rng.uniform(0.5, 10.0, size=30))[::-1].copy()
        scale = float(np.linalg.norm(S) * 1.2)
        eps_scaled = 0.05
        est = np.asarray(singular_value_estimates(
            key, S, scale, eps_scaled, n_features=64))
        # decoding derivative bound: |dσ/dθ| ≤ scale·(ε+π)/2; consistent PE
        # grid width ε ⇒ σ error ≤ scale·ε·(ε+π)/2 (plus snap rounding)
        tol = scale * eps_scaled * (eps_scaled + np.pi)
        assert np.max(np.abs(est - S)) < tol

    def test_spectral_norm_estimation(self, data):
        pca = QPCA(n_components=10, random_state=0).fit(
            data, spectral_norm_est=True, eps=0.5, delta=0.01)
        true = pca.spectral_norm
        assert abs(pca.est_spectral_norm - true) / true < 0.15

    def test_condition_number_estimation(self, data):
        pca = QPCA(random_state=0).fit(
            data, condition_number_est=True, eps=0.1, delta=0.001, p=0.999)
        # the estimator brackets the genuine smallest singular value of A
        # (the full spectrum, not the retained slice); binary search
        # bracket width limits precision
        sigma_min = pca.all_singular_values_[-1]
        assert pca.est_sigma_min == pytest.approx(sigma_min, rel=1.0)
        assert pca.est_cond_number == pytest.approx(
            pca.spectral_norm / pca.est_sigma_min)

    def test_factor_score_ratio_sum(self, data):
        # full spectrum (n_components = min shape) so the ratio denominator
        # covers everything; θ sits in the huge signal/noise spectral gap at
        # index 20 where PE error cannot flip selections
        pca = QPCA(n_components=30, random_state=0, compute_mu=True).fit(data)
        S = pca.singular_values_
        theta = 0.5 * (S[19] + S[20]) / pca.muA
        p_est = pca.quantum_factor_score_ratio_sum(
            eps=0.01, theta=theta, eta=0.01)
        p_true = float(np.sum(S[:20] ** 2) / np.sum(S**2))
        assert abs(p_est - p_true) < 0.05

    def test_estimate_theta_binary_search(self, data):
        p_target = 0.8
        pca = QPCA(random_state=0).fit(
            data, theta_estimate=True, eps_theta=0.05, eta=0.05, p=p_target)
        # retained mass above est_theta should be ≈ p_target
        S = pca.singular_values_
        mass = np.sum(S[S >= pca.est_theta] ** 2) / np.sum(S**2)
        assert abs(mass - p_target) < 0.15

    def test_estimate_all_gaussian(self, data):
        pca = QPCA(n_components=8, random_state=0).fit(
            data, estimate_all=True, eps=0.01, delta=0.05,
            theta_major=1e-6, true_tomography=False)
        assert pca.topk == 8
        # tomography at δ ⇒ per-row L2 error ≲ δ
        err = np.linalg.norm(pca.estimate_right_sv - pca.components_, axis=1)
        assert np.all(err < 0.2)
        np.testing.assert_allclose(
            np.sum(pca.estimate_fs_ratio),
            np.sum(pca.explained_variance_ratio_all[:8]), atol=0.1)

    def test_estimate_all_true_tomography_small(self):
        rng = np.random.default_rng(3)
        X = rng.normal(size=(60, 8))
        pca = QPCA(n_components=3, random_state=0).fit(
            X, estimate_all=True, eps=0.01, delta=0.3, theta_major=1e-6,
            true_tomography=True)
        err = np.linalg.norm(pca.estimate_right_sv - pca.components_, axis=1)
        assert np.all(err < 0.45)  # δ-close w.h.p., unit-norm rows

    def test_least_k_extraction(self, data):
        pca = QPCA(random_state=0).fit(
            data, estimate_least_k=True, eps=0.01, delta=0.05,
            theta_minor=5.0, true_tomography=False, p=0.999)
        S = pca.singular_values_
        expected = int(np.sum(S[~np.isclose(S, 0)] < 5.0))
        # PE error can move boundary σ across θ; count is approximate
        assert abs(pca.least_k - expected) <= 2
        assert pca.estimate_least_right_sv.shape[1] == data.shape[1]

    def test_delta_eps_zero_is_classical(self, data):
        pca = QPCA(n_components=5, random_state=0).fit(
            data, estimate_all=True, eps=0, delta=0, theta_major=1e-9)
        np.testing.assert_allclose(pca.estimate_right_sv, pca.components_)
        np.testing.assert_allclose(pca.estimate_s_values,
                                   pca.singular_values_)


class TestQuantumTransform:
    @pytest.fixture(scope="class")
    def fitted(self, data):
        return QPCA(n_components=5, random_state=0).fit(
            data, estimate_all=True, eps=0.01, delta=0.02,
            theta_major=1e-6, true_tomography=False)

    def test_classic_transform_warns_on_quantum_args(self, fitted, data):
        with pytest.warns(UserWarning, match="quantum parameter"):
            fitted.transform(data, classic_transform=True, epsilon_delta=0.5)

    def test_estimated_components_projection(self, fitted, data):
        Xt_q = fitted.transform(data, classic_transform=False,
                                use_classical_components=False)
        Xt_c = fitted.transform(data)
        assert Xt_q.shape == Xt_c.shape
        # estimated components are δ-close ⇒ projections close relatively
        rel = np.linalg.norm(Xt_q - Xt_c) / np.linalg.norm(Xt_c)
        assert rel < 0.1

    def test_quantum_representation_none(self, fitted, data):
        Xt = fitted.transform(data, classic_transform=False,
                              quantum_representation=True, norm="None",
                              psi=0.1, epsilon_delta=0.1,
                              true_tomography=False)
        Y = Xt["quantum_representation_results"]
        assert Y.shape == (len(data), 5)

    def test_quantum_representation_est(self, fitted, data):
        Xt = fitted.transform(data, classic_transform=False,
                              quantum_representation=True,
                              norm="est_representation", psi=0,
                              epsilon_delta=0.1, true_tomography=False)
        A_sign, eps_delta, f_norm = Xt["quantum_representation_results"]
        assert A_sign.shape == (len(data), 5)
        assert f_norm >= 0

    def test_quantum_representation_q_state(self, fitted, data):
        Xt = fitted.transform(data[:16], classic_transform=False,
                              quantum_representation=True, norm="q_state",
                              psi=0.1, epsilon_delta=0.1,
                              true_tomography=False)
        qs = Xt["quantum_representation_results"]
        probs = np.asarray(qs.probabilities)
        assert probs.shape == (16,)
        np.testing.assert_allclose(probs.sum(), 1.0, atol=1e-5)

    def test_quantum_representation_f_norm(self, fitted, data):
        Xt = fitted.transform(data, classic_transform=False,
                              quantum_representation=True, norm="f_norm",
                              psi=0.1, epsilon_delta=0.1,
                              true_tomography=False)
        Y = Xt["quantum_representation_results"]
        np.testing.assert_allclose(np.linalg.norm(Y), 1.0, rtol=1e-5)

    def test_inverse_transform_estimated(self, fitted, data):
        Xt = fitted.transform(data)
        Xr_c = fitted.inverse_transform(Xt)
        Xr_q = fitted.inverse_transform(Xt, use_classical_components=False)
        rel = np.linalg.norm(Xr_q - Xr_c) / np.linalg.norm(Xr_c)
        assert rel < 0.1


class TestCompatFitKwargs:
    """The reference's stored-only / debug fit kwargs (``_qPCA.py:357-362``)
    are accepted; the plt.show() diagnostic becomes stored ratio arrays
    (documented intent, not the reference's selected-slice/full-array
    shape bug at ``_qPCA.py:1042``)."""

    def test_sv_uniform_distribution_stored_per_side(self, data):
        pca = QPCA(random_state=0).fit(
            data, estimate_all=True, estimate_least_k=True, eps=0.05,
            delta=0.05, theta_major=1e-6, theta_minor=3.0,
            true_tomography=False, check_sv_uniform_distribution=True,
            use_computed_qcomponents=True, fs_ratio_estimation=True)
        # stored no-op flags round-trip verbatim
        assert pca.use_computed_qcomponents is True
        assert pca.fs_ratio_estimation is True
        # per-side ratios align with each selected slice (the reference
        # divides the slice by the full array and would crash)
        assert pca.sv_uniform_distribution_.shape == (pca.topk,)
        assert pca.least_k_sv_uniform_distribution_.shape == (pca.least_k,)
        # direction: sigma_true / sigma_hat, so near-exact estimates ≈ 1
        assert np.all(np.abs(pca.sv_uniform_distribution_ - 1.0) < 0.5)

    def test_sv_uniform_distribution_cleared_on_refit(self, data):
        pca = QPCA(random_state=0).fit(
            data, estimate_all=True, eps=0.05, delta=0.05,
            theta_major=1e-6, true_tomography=False,
            check_sv_uniform_distribution=True)
        assert hasattr(pca, "sv_uniform_distribution_")
        # refit whose extractor never runs must drop the stale diagnostic
        # even with the flag still on
        pca.fit(data, check_sv_uniform_distribution=True)
        assert not hasattr(pca, "sv_uniform_distribution_")
        assert not hasattr(pca, "least_k_sv_uniform_distribution_")

    def test_zero_sigma_ratio_is_nan(self):
        from sq_learn_tpu.models.qpca import _sv_ratio

        out = _sv_ratio(np.array([1.0, 2.0]), np.array([0.0, 2.0]))
        assert np.isnan(out[0]) and out[1] == 1.0


class TestRuntimeModel:
    def test_accumulate_and_compare(self, data, tmp_path):
        # p targets the top-3 mass step of the retained 5-value spectrum
        # (≈0.686): the θ search converges from the true masses alone. 0.8
        # sits between steps (0.686/0.853), where success hinges on a lucky
        # AE draw — fragile under any RNG-stream change.
        pca = QPCA(n_components=5, random_state=0).fit(
            data, estimate_all=True, theta_estimate=True,
            quantum_retained_variance=True, eps=0.1, eps_theta=0.1,
            eta=0.1, delta=0.1, p=0.7, true_tomography=False)
        n, m, q_rt, c_rt = pca.runtime_comparison(
            10_000, 1_000, saveas=str(tmp_path / "rt.png"))
        assert q_rt.shape == (100, 100)
        assert np.all(np.isfinite(q_rt))
        assert (tmp_path / "rt.png").exists()

    def test_q_ret_variance(self, data):
        pca = QPCA(random_state=0).fit(data, p=0.9)
        k = pca.q_ret_variance(100_000, 0.9)
        assert abs(k - pca.n_components_) <= 2

    def test_runtime_container_not_double_counted(self, data):
        pca = QPCA(n_components=5, random_state=0).fit(
            data, estimate_all=True, eps=0.1, delta=0.1, theta_major=1e-6,
            true_tomography=False)
        _, _, q1, _ = pca.runtime_comparison(1000, 100)
        _, _, q2, _ = pca.runtime_comparison(1000, 100)
        np.testing.assert_allclose(q1, q2)


class TestValidation:
    def test_none_components_keeps_full_spectrum(self, data):
        # the reference collapses n_components=None without p to a single
        # component (_qPCA.py:620-623); stock semantics keep everything
        pca = PCA().fit(data)
        assert pca.n_components_ == min(data.shape)

    def test_estimate_all_requires_theta(self, data):
        with pytest.raises(ValueError, match="theta_major"):
            QPCA(n_components=3).fit(data, estimate_all=True, eps=0.1,
                                     delta=0.1)

    def test_least_k_requires_theta_minor(self, data):
        with pytest.raises(ValueError, match="theta_minor"):
            QPCA(n_components=3).fit(data, estimate_least_k=True, eps=0.1,
                                     delta=0.1)

    def test_eps_zero_estimators_exact(self, data):
        pca = QPCA(n_components=5, random_state=0).fit(
            data, spectral_norm_est=True, condition_number_est=True,
            eps=0, delta=0)
        assert pca.est_spectral_norm == pca.spectral_norm
        assert pca.est_sigma_min == pytest.approx(
            float(pca.all_singular_values_[-1]))


def test_fit_transform_forwards_quantum_kwargs():
    """The reference's fit_transform crashes on stale kwargs
    (_qPCA.py:467-473); ours forwards everything (documented intent)."""
    from sq_learn_tpu.datasets import make_blobs

    X, _ = make_blobs(n_samples=200, centers=3, n_features=16,
                      cluster_std=0.8, random_state=0)
    pca = QPCA(n_components=4, random_state=0)
    Xt = pca.fit_transform(
        X, estimate_all=True, theta_major=1e-9, eps=0.05, delta=0.05,
        true_tomography=False, classic_transform=False,
        use_classical_components=False)
    assert Xt.shape == (200, 4)
    assert hasattr(pca, "estimate_right_sv")
    # classical default path still works
    Xt2 = QPCA(n_components=4, random_state=0).fit_transform(X)
    assert Xt2.shape == (200, 4)


def test_mle_tied_eigenvalues_raise_loudly():
    """Exactly tied eigenvalues make the Laplace evidence diverge; the
    estimator must fail with a clear message, not pick a corrupt rank."""
    from sq_learn_tpu.models.qpca import _assess_dimension

    spec = np.array([5.0, 5.0, 2.0, 1.0, 0.5])
    with pytest.raises(ValueError, match="tied eigenvalues"):
        _assess_dimension(spec, 2, 100)


class TestComputeDtypeQPCA:
    def test_bfloat16_gram_route(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(2000, 32)).astype(np.float32)
        ref = QPCA(n_components=4, svd_solver="full").fit(X)
        bf = QPCA(n_components=4, svd_solver="full",
                  compute_dtype="bfloat16").fit(X)
        np.testing.assert_allclose(bf.explained_variance_ratio_,
                                   ref.explained_variance_ratio_, rtol=5e-2)
        # components agree up to bf16-scale error after sign alignment
        sgn = np.sign(np.sum(bf.components_ * ref.components_, axis=1))
        err = np.abs(bf.components_ * sgn[:, None] - ref.components_).max()
        assert err < 0.1, err

    def test_non_gram_route_warns(self):
        rng = np.random.default_rng(1)
        X = rng.normal(size=(100, 32)).astype(np.float32)  # aspect < 8
        with pytest.warns(RuntimeWarning, match="partial-U Gram route"):
            QPCA(n_components=4, svd_solver="full",
                 compute_dtype="bfloat16").fit(X)


class TestCovariancePrecisionScore:
    """get_covariance / get_precision / score_samples parity with sklearn
    (reference modified _BasePCA carries the first two, _base.py:25-77)."""

    def test_matches_sklearn(self):
        import sklearn.decomposition

        rng = np.random.default_rng(0)
        X = rng.normal(size=(300, 12)).astype(np.float64)
        ours = QPCA(n_components=4, svd_solver="full").fit(X)
        ref = sklearn.decomposition.PCA(n_components=4,
                                        svd_solver="full").fit(X)
        np.testing.assert_allclose(ours.get_covariance(),
                                   ref.get_covariance(), rtol=1e-3,
                                   atol=1e-4)
        np.testing.assert_allclose(ours.get_precision(),
                                   ref.get_precision(), rtol=1e-3,
                                   atol=1e-3)
        np.testing.assert_allclose(ours.score_samples(X[:20]),
                                   ref.score_samples(X[:20]), rtol=1e-3,
                                   atol=1e-2)
        assert ours.score(X) == pytest.approx(ref.score(X), rel=1e-3)

    def test_precision_is_inverse(self):
        rng = np.random.default_rng(1)
        X = rng.normal(size=(200, 8)).astype(np.float32)
        pca = QPCA(n_components=3, svd_solver="full").fit(X)
        prod = pca.get_covariance() @ pca.get_precision()
        np.testing.assert_allclose(prod, np.eye(8), atol=5e-3)
