"""Control-plane contract tests (ISSUE 17).

The load-bearing ones: ``autotune=False`` / ``SQ_SERVE_AUTOTUNE=0`` pin
the static PR 16 serving plane bit-identically (same responses, no
route overrides, zero ``control`` records); with ``SQ_OBS`` unset the
registry allocates NO controller state at all (the PR 12 disabled-path
rule); the plan-time frontier pick lands the cheapest route inside the
declared ε; the degrade ladder steps cheapest-first with renegotiated
ledger targets that re-base the burn; relax/tighten move the served δ
only inside the declared headroom; and every decision is a schema-v8
``control`` record with a per-tenant monotonic seq and a realized
follow-up one evaluation later.
"""

import gzip
import json
import shutil

import numpy as np
import pytest

from sq_learn_tpu import obs
from sq_learn_tpu.models import QKMeans
from sq_learn_tpu.obs.budget import BudgetLedger
from sq_learn_tpu.obs.schema import validate_jsonl, validate_record
from sq_learn_tpu.obs.trace import load_jsonl
from sq_learn_tpu.serving import MicroBatchDispatcher, ModelRegistry
from sq_learn_tpu.serving import cache as serve_cache
from sq_learn_tpu.serving import control
from sq_learn_tpu.serving.control import Controller, theoretical_cost


@pytest.fixture(scope="module")
def fitted():
    rng = np.random.default_rng(0)
    m = 12
    X = (rng.normal(size=(300, m))
         + 6.0 * rng.integers(0, 3, size=(300, 1))).astype(np.float32)
    qkm = QKMeans(n_clusters=3, random_state=0, n_init=1).fit(X)
    return {"X": X, "m": m, "qkm": qkm}


@pytest.fixture(autouse=True)
def _hygiene():
    serve_cache.clear()
    yield
    serve_cache.clear()
    if obs.enabled():
        obs.disable()


class _StubDispatcher:
    """The two geometry attributes + ledger accessor the controller
    reads — unit tests drive `evaluate` without a serving stack."""

    _min_bucket = 8
    _max_batch_rows = 128

    def __init__(self, led):
        self._led = led

    def budget_ledger(self):
        return self._led


def _reqs(fitted, n=12, sizes=(1, 5, 17)):
    rng = np.random.default_rng(3)
    return [rng.normal(size=(sizes[i % len(sizes)], fitted["m"]))
            .astype(np.float32) for i in range(n)]


# -- cost model --------------------------------------------------------------


def test_theoretical_cost_scales_inverse_delta_squared():
    assert theoretical_cost(None) is None
    assert theoretical_cost(0.0) is None
    assert theoretical_cost(-1.0) is None
    assert theoretical_cost(1e-3) == pytest.approx(1e6)
    # halving δ quadruples the theoretical runtime (the runtime model's
    # non-well-clusterable 1/δ² terms)
    assert theoretical_cost(5e-4) == pytest.approx(4e6)
    # quantized routes scale by their transfer weight
    assert theoretical_cost(1e-3, "bf16") == pytest.approx(0.5e6)
    assert theoretical_cost(1e-3, "int8") == pytest.approx(0.25e6)


# -- plan: the register/warm-time frontier pick ------------------------------


def test_plan_picks_cheapest_route_inside_eps(fitted):
    rec = obs.enable()
    reg = ModelRegistry()
    reg.register("wide", fitted["qkm"], quantize=None, slo_eps=0.01)
    reg.register("narrow", fitted["qkm"], quantize=None, slo_eps=0.00392)
    reg.register("tight", fitted["qkm"], quantize=None, slo_eps=1e-4)
    reg.register("blank", fitted["qkm"], quantize=None)
    ctl = reg.controller()
    for t in ("wide", "narrow", "tight", "blank"):
        ctl.plan(t)
    # int8 (cost 0.25) fits 0.01; only bf16 fits the narrow window;
    # nothing quantized fits 1e-4; no declared ε = route untouched
    assert reg.current_route("wide") == "int8"
    assert reg.current_route("narrow") == "bf16"
    assert reg.current_route("tight") is None
    assert reg.current_route("blank") is None
    plans = {r["tenant"]: r for r in rec.control_records
             if r["action"] == "plan"}
    # a silent controller is indistinguishable from a dead one: the
    # no-headroom tenant still lands its (no-op) plan record
    assert set(plans) == {"wide", "narrow", "tight", "blank"}
    assert plans["wide"]["decision"]["route"] == "int8"
    assert plans["blank"]["decision"]["route"] == "exact"
    for r in rec.control_records:
        assert validate_record(r) == [], r
    obs.disable()


def test_plan_idempotent_until_replan(fitted):
    rec = obs.enable()
    reg = ModelRegistry()
    reg.register("t", fitted["qkm"], quantize=None, slo_eps=0.01)
    ctl = reg.controller()
    ctl.plan("t")
    ctl.plan("t")  # second call: no new record, no seq burn
    assert len([r for r in rec.control_records
                if r["action"] == "plan"]) == 1
    # a re-register re-contracts: the registry itself replans (the
    # binding changed under the controller), re-reading the declaration
    reg.register("t", fitted["qkm"], quantize=None, slo_eps=1e-4)
    plans = [r for r in rec.control_records if r["action"] == "plan"]
    assert len(plans) == 2
    assert plans[-1]["decision"]["route"] == "exact"
    assert reg.current_route("t") is None
    obs.disable()


# -- evaluate: the cadence ladder --------------------------------------------


def test_degrade_ladder_widen_host_and_renegotiation(fitted):
    """An exact-route tenant with no ε headroom burns: the ladder must
    step widen → host (the quantize rung needs declared ε), each rung
    renegotiating the ledger targets so the re-based burn lands under
    the relax threshold."""
    rec = obs.enable()
    reg = ModelRegistry()
    reg.register("t", fitted["qkm"], quantize=None, slo_p99_ms=1.0)
    ctl = Controller(reg, patience=1)
    led = BudgetLedger(window_seconds=(1.0,), site="test")
    d = _StubDispatcher(led)

    led.note_requests("t", [0.5] * 10, p99_ms=1.0, ts=100.0)
    acts = dict(ctl.evaluate(d, now=100.0))
    assert acts["t"] == "degrade"
    assert ctl.min_rows_for("t", 8) == 64  # max(8*4, min(128, 64))
    assert not ctl.host_route("t")
    p50_t, p99_t = ctl.targets_for("t")
    assert p50_t is None
    assert p99_t == pytest.approx(500.0 * control.RENEGOTIATE_MARGIN)

    # renegotiated targets re-base the ledger burn: the same 500 ms
    # latencies now sit inside the 1000 ms target
    led.note_requests("t", [0.5] * 10, ts=100.5)
    stats = led.window_stats("t", 1.0, now=101.4)
    assert stats["slo_burn_rate"] == 0.0

    # a second burn (fresh window, tiny renegotiated target restored by
    # noting an over-target batch) takes the last rung: host
    led.note_requests("t", [5.0] * 10, ts=102.0)
    acts = dict(ctl.evaluate(d, now=102.0))
    assert acts["t"] == "degrade"
    assert ctl.host_route("t")

    records = [r for r in rec.control_records if r["tenant"] == "t"]
    degrades = [r for r in records if r["action"] == "degrade"]
    assert [r["level"] for r in degrades] == [1, 2]
    assert degrades[0]["decision"]["min_rows"] == 64
    assert degrades[1]["decision"]["route"] == "host"
    # predicted effect of a renegotiation: burn at 1/margin
    assert degrades[0]["predicted"]["burn_rate"] == pytest.approx(
        1.0 / control.RENEGOTIATE_MARGIN)
    seqs = [r["seq"] for r in records]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    for r in records:
        assert validate_record(r) == [], r
    obs.disable()


def test_quantize_rung_inside_declared_eps(fitted):
    """The cheapest rung: an ε-headroom tenant whose route override was
    cleared (operator action) degrades into the quantized route before
    any coalescing or host fallback."""
    obs.enable()
    reg = ModelRegistry()
    reg.register("q", fitted["qkm"], quantize=None, slo_eps=0.01,
                 slo_p99_ms=1.0)
    ctl = Controller(reg, patience=1)
    ctl.plan("q")
    assert reg.current_route("q") == "int8"
    reg.set_route_override("q", None)  # operator cleared the pick
    led = BudgetLedger(window_seconds=(1.0,), site="test")
    led.note_requests("q", [0.5] * 10, p99_ms=1.0, ts=10.0)
    acts = dict(ctl.evaluate(_StubDispatcher(led), now=10.0))
    assert acts["q"] == "degrade"
    assert reg.current_route("q") == "bf16"  # exact → bf16, not host
    assert ctl.min_rows_for("q", 8) == 8
    assert not ctl.host_route("q")
    obs.disable()


def test_recover_steps_back_most_recent_first(fitted):
    rec = obs.enable()
    reg = ModelRegistry()
    reg.register("t", fitted["qkm"], quantize=None, slo_p99_ms=1.0)
    ctl = Controller(reg, patience=1)
    led = BudgetLedger(window_seconds=(1.0,), site="test")
    d = _StubDispatcher(led)
    led.note_requests("t", [0.5] * 10, p99_ms=1.0, ts=0.0)
    assert dict(ctl.evaluate(d, now=0.0))["t"] == "degrade"  # widen
    led.note_requests("t", [5.0] * 10, ts=2.0)
    assert dict(ctl.evaluate(d, now=2.0))["t"] == "degrade"  # host
    assert ctl.host_route("t")
    # healthy traffic inside the renegotiated target, old window pruned
    led.note_requests("t", [0.5] * 10, ts=4.0)
    assert dict(ctl.evaluate(d, now=4.0))["t"] == "recover"
    assert not ctl.host_route("t")  # most recent rung undone first
    assert ctl.min_rows_for("t", 8) == 64  # widen still applied
    led.note_requests("t", [0.5] * 10, ts=6.0)
    assert dict(ctl.evaluate(d, now=6.0))["t"] == "recover"
    assert ctl.min_rows_for("t", 8) == 8
    # fully recovered: renegotiated targets dropped
    assert ctl.targets_for("t") is None
    # the realized follow-up closed the loop on the first degrade
    realized = [r for r in rec.control_records
                if r["tenant"] == "t" and isinstance(r.get("realized"),
                                                     dict)]
    assert realized and all(
        isinstance(r["realized"].get("burn_rate"), (int, float))
        for r in realized)
    obs.disable()


def test_relax_banks_delta_then_tighten_walks_back(fitted):
    obs.enable()
    reg = ModelRegistry()
    reg.register("b", fitted["qkm"], quantize=None, slo_delta=1e-3,
                 slo_p99_ms=1e6)
    ctl = Controller(reg, patience=1)
    led = BudgetLedger(window_seconds=(1.0,), site="test")
    d = _StubDispatcher(led)
    # persistently underspent: relax doubles δ toward the 4× cap
    led.note_requests("b", [1e-6], p99_ms=1e6, ts=0.0)
    assert dict(ctl.evaluate(d, now=0.0))["b"] == "relax"
    led.note_requests("b", [1e-6], ts=0.2)
    assert dict(ctl.evaluate(d, now=0.2))["b"] == "relax"
    c = ctl.contracts()["b"]
    assert c["delta_declared"] == pytest.approx(1e-3)
    assert c["delta_served"] == pytest.approx(4e-3)  # at the cap
    # banked theoretical runtime: cost_served is 16× under cost_declared
    assert c["cost_declared"] / c["cost_served"] == pytest.approx(16.0)
    # at the cap: no further relax
    led.note_requests("b", [1e-6], ts=0.4)
    assert dict(ctl.evaluate(d, now=0.4))["b"] == "hold"
    # the draw stream turns statistically inconsistent: tighten halves
    # δ back toward the declaration before the audit can flag it
    for i in range(20):
        led.note_draw("b", True, fail_prob=1e-3, ts=0.5)
    led.note_requests("b", [1e-6], ts=0.5)
    assert dict(ctl.evaluate(d, now=0.5))["b"] == "tighten"
    assert ctl.contracts()["b"]["delta_served"] == pytest.approx(2e-3)
    obs.disable()


def test_no_headroom_tenant_never_recontracted(fitted):
    """A tenant that declared nothing gets hold records only — its δ
    and route are controller-invariant by construction."""
    rec = obs.enable()
    reg = ModelRegistry()
    reg.register("p", fitted["qkm"], quantize=None, slo_p99_ms=1e6)
    ctl = Controller(reg, patience=1)
    led = BudgetLedger(window_seconds=(1.0,), site="test")
    d = _StubDispatcher(led)
    for i in range(4):
        led.note_requests("p", [1e-6], p99_ms=1e6, ts=float(i) / 10)
        ctl.evaluate(d, now=float(i) / 10)
    c = ctl.contracts()["p"]
    assert c["delta_served"] is None and c["cost_served"] is None
    assert c["route"] == "exact" and c["level"] == 0
    acts = {r["action"] for r in rec.control_records
            if r["tenant"] == "p"}
    assert acts == {"plan", "hold"}
    obs.disable()


# -- the static-plane pins ---------------------------------------------------


def test_autotune_off_is_bit_identical_and_silent(fitted, monkeypatch):
    """``autotune=False`` (and ``SQ_SERVE_AUTOTUNE=0``) pin the PR 16
    plane: responses bit-equal to a no-obs run, no route override on an
    ε-headroom tenant, zero control records."""
    reqs = _reqs(fitted)

    def run(autotune, observe):
        serve_cache.clear()
        reg = ModelRegistry()
        reg.register("t", fitted["qkm"], quantize=None, slo_eps=0.01,
                     slo_p99_ms=1e-6)  # would burn AND re-route if tuned
        if observe:
            obs.enable()
        d = MicroBatchDispatcher(reg, background=False,
                                 max_batch_rows=64, autotune=autotune,
                                 autotune_every=1)
        outs = [d.serve("t", "predict", r) for r in reqs]
        d.close()
        rec = obs.disable() if observe else None
        return outs, reg, rec

    base, reg0, _ = run(autotune=False, observe=False)
    off, reg1, rec1 = run(autotune=False, observe=True)
    assert all(np.array_equal(a, b) for a, b in zip(base, off))
    assert reg1.current_route("t") is None
    assert rec1.control_records == []
    assert reg1.controller(create=False) is None

    # the env kill switch latches the same static plane
    monkeypatch.setenv("SQ_SERVE_AUTOTUNE", "0")
    env_off, reg2, rec2 = run(autotune=None, observe=True)
    assert all(np.array_equal(a, b) for a, b in zip(base, env_off))
    assert rec2.control_records == []
    monkeypatch.delenv("SQ_SERVE_AUTOTUNE")

    # tuned run on the same traffic: the plan re-routes the tenant
    on, reg3, rec3 = run(autotune=True, observe=True)
    assert len(on) == len(base)  # zero requests lost either way
    assert any(r["action"] == "plan" for r in rec3.control_records)
    assert reg3.current_route("t") == "int8"


def test_disabled_path_allocates_no_controller(fitted):
    """With SQ_OBS unset the controller must not exist at all: the
    registry returns None, the dispatcher never materializes one."""
    assert not obs.enabled()
    reg = ModelRegistry()
    reg.register("t", fitted["qkm"], quantize=None, slo_eps=0.01)
    assert reg.controller() is None
    assert reg.controller(create=False) is None
    d = MicroBatchDispatcher(reg, background=False, autotune=True,
                             autotune_every=1)
    for r in _reqs(fitted, n=4):
        d.serve("t", "predict", r)
    d.close()
    assert d._ctl is None
    assert reg.controller(create=False) is None
    assert reg.current_route("t") is None  # no plan ever ran


# -- schema v8 + gzip artifacts ----------------------------------------------


def test_control_record_schema_v8():
    good = {"v": 8, "schema_version": 8, "ts": 0.0, "type": "control",
            "tenant": "t",
            "action": "degrade", "seq": 3, "level": 1,
            "inputs": {"burn_rate": 2.0}, "decision": {"route": "host"},
            "predicted": {"burn_rate": 0.5},
            "realized": {"burn_rate": 0.4}}
    assert validate_record(good) == []
    bad_action = dict(good, action="explode")
    assert any("action" in e for e in validate_record(bad_action))
    bad_seq = dict(good, seq=-1)
    assert validate_record(bad_seq) != []
    missing = {k: v for k, v in good.items() if k != "inputs"}
    assert validate_record(missing) != []


def test_budget_and_alert_seq_optional_but_typed():
    budget = {"v": 7, "schema_version": 7, "ts": 0.0, "type": "budget",
              "tenant": "t", "window_s": 60.0, "slo_burn": 0.1,
              "stat_burn": None, "cp_lower_bound": None,
              "burn_rate": 0.2, "alerting": False}
    assert validate_record(budget) == []  # v7 shape: no seq yet
    v8 = dict(budget, v=8, schema_version=8)
    assert validate_record(dict(v8, seq=4)) == []
    assert validate_record(dict(v8, seq="x")) != []
    alert = {"v": 7, "schema_version": 7, "ts": 0.0, "type": "alert",
             "tenant": "t", "kind": "slo_burn",
             "burn_rates": {"60": 2.5}, "threshold": 2.0}
    assert validate_record(alert) == []
    a8 = dict(alert, v=8, schema_version=8)
    assert validate_record(dict(a8, seq=1)) == []
    assert validate_record(dict(a8, seq=-2)) != []


def test_budget_emit_stamps_monotonic_seq():
    rec = obs.enable()
    led = BudgetLedger(window_seconds=(1.0,), site="test")
    led.note_requests("t", [1e-6], p99_ms=1e3, ts=0.0)
    led.emit(now=0.1)
    led.note_requests("t", [1e-6], ts=0.2)
    led.emit(now=0.3)
    obs.disable()
    seqs = [r["seq"] for r in rec.budget_records]
    assert all(isinstance(s, int) for s in seqs)
    assert seqs == sorted(seqs)
    # strictly increasing across emits (per-emit batches share a seq
    # epoch only if the recorder says so — assert per-record uniqueness
    # within a tenant+window stream, the replay-order key)
    stream = [(r["tenant"], r["window_s"], r["seq"])
              for r in rec.budget_records]
    assert len(set(stream)) == len(stream)


def test_jsonl_readers_open_gzip_transparently(tmp_path, fitted):
    path = str(tmp_path / "run.jsonl")
    obs.enable(path)
    reg = ModelRegistry()
    reg.register("t", fitted["qkm"], quantize=None, slo_eps=0.01,
                 slo_p99_ms=1e6)
    d = MicroBatchDispatcher(reg, background=False, autotune=True,
                             autotune_every=2)
    for r in _reqs(fitted, n=6):
        d.serve("t", "predict", r)
    d.close()
    obs.disable()

    gz = str(tmp_path / "run.jsonl.gz")
    with open(path, "rb") as src, gzip.open(gz, "wb") as dst:
        shutil.copyfileobj(src, dst)

    plain = validate_jsonl(path)
    packed = validate_jsonl(gz)
    assert plain["errors"] == [] and packed["errors"] == []
    assert packed["by_type"] == plain["by_type"]
    assert packed["by_type"].get("control", 0) >= 1
    assert load_jsonl(gz) == load_jsonl(path)


def test_control_cli_renders_and_exits(tmp_path, capsys, fitted):
    from sq_learn_tpu.obs import control as obs_control

    path = str(tmp_path / "c.jsonl")
    obs.enable(path)
    reg = ModelRegistry()
    reg.register("t", fitted["qkm"], quantize=None, slo_eps=0.01)
    reg.controller().plan("t")
    obs.disable()
    assert obs_control.main([path]) == 0
    out = capsys.readouterr().out
    assert "t" in out and "plan" in out
    empty = str(tmp_path / "empty.jsonl")
    with open(empty, "w") as fh:
        fh.write(json.dumps({"ts": 0.0, "type": "counter", "name": "x",
                             "value": 1, "delta": 1}) + "\n")
    assert obs_control.main([empty]) == 2
