"""Pallas fused-Lloyd kernel tests (interpreter mode on CPU; the same code
path compiles for the MXU on a real TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sq_learn_tpu.datasets import make_blobs
from sq_learn_tpu.models import KMeans
from sq_learn_tpu.models.qkmeans import e_step, m_step
from sq_learn_tpu.ops.linalg import row_norms
from sq_learn_tpu.ops.pallas_kernels import lloyd_step_pallas


@pytest.fixture(scope="module")
def problem():
    rng = np.random.default_rng(11)
    X = rng.normal(size=(700, 17)).astype(np.float32)  # deliberately unaligned
    C = X[rng.choice(700, 5, replace=False)]
    w = np.ones(700, np.float32)
    return (jnp.asarray(X), jnp.asarray(w), jnp.asarray(C),
            row_norms(jnp.asarray(X), squared=True))


class TestFusedKernelEquivalence:
    def test_matches_xla_estep_mstep(self, problem, key):
        X, w, C, xsq = problem
        labels_p, mind2_p, sums, counts, inertia_p = lloyd_step_pallas(
            X, w, C, xsq, interpret=True)

        labels_x, inertia_x, mind2_x = e_step(
            key, X, w, C, xsq, delta=0.0, mode="classic", ipe_q=1)
        np.testing.assert_array_equal(np.asarray(labels_p),
                                      np.asarray(labels_x))
        np.testing.assert_allclose(np.asarray(mind2_p), np.asarray(mind2_x),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(float(inertia_p), float(inertia_x),
                                   rtol=1e-4)
        new_centers_x = m_step(key, X, w, labels_x, C, delta=0.0,
                               intermediate_error=False, true_tomography=True)
        safe = jnp.where(counts > 0, counts, 1.0)
        new_centers_p = jnp.where((counts > 0)[:, None],
                                  sums / safe[:, None], C)
        np.testing.assert_allclose(np.asarray(new_centers_p),
                                   np.asarray(new_centers_x),
                                   rtol=1e-4, atol=1e-5)

    def test_zero_weight_rows_ignored(self, problem):
        X, w, C, xsq = problem
        w2 = w.at[:100].set(0.0)
        _, _, sums, counts, inertia = lloyd_step_pallas(
            X, w2, C, xsq, interpret=True)
        _, _, sums_ref, counts_ref, inertia_ref = lloyd_step_pallas(
            X[100:], w[100:], C, xsq[100:], interpret=True)
        np.testing.assert_allclose(np.asarray(sums), np.asarray(sums_ref),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(counts),
                                   np.asarray(counts_ref), rtol=1e-5)
        np.testing.assert_allclose(float(inertia), float(inertia_ref),
                                   rtol=1e-4)

    def test_weighted_samples(self, problem, key):
        X, w, C, xsq = problem
        w3 = jax.random.uniform(key, w.shape, minval=0.1, maxval=3.0)
        labels_p, _, sums, counts, _ = lloyd_step_pallas(
            X, w3, C, xsq, interpret=True)
        onehot = jax.nn.one_hot(labels_p, C.shape[0]) * w3[:, None]
        np.testing.assert_allclose(np.asarray(jnp.sum(onehot, axis=0)),
                                   np.asarray(counts), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(onehot.T @ X),
                                   np.asarray(sums), rtol=1e-3, atol=1e-3)


class TestBf16Kernel:
    def test_bf16_matches_f32_on_separated_blobs(self):
        """compute_dtype='bfloat16' feeds the MXU its native dtype; on
        data whose cluster margins dwarf bf16 rounding the labels must
        match the f32 kernel exactly, with f32-accumulated partials close."""
        X, _ = make_blobs(n_samples=300, centers=4, n_features=16,
                          cluster_std=0.5, random_state=7)
        Xd = jnp.asarray(X)
        w = jnp.ones(300, jnp.float32)
        C = Xd[:4]
        xsq = row_norms(Xd, squared=True)
        l32, _, s32, c32, i32 = lloyd_step_pallas(
            Xd, w, C, xsq, interpret=True)
        l16, _, s16, c16, i16 = lloyd_step_pallas(
            Xd, w, C, xsq, interpret=True, compute_dtype="bfloat16")
        # a point sitting exactly on a Voronoi boundary may flip under
        # bf16 rounding; anything beyond stray boundary flips is a bug
        flips = np.mean(np.asarray(l16) != np.asarray(l32))
        assert flips <= 0.01, f"{flips:.1%} labels flipped under bf16"
        np.testing.assert_allclose(np.asarray(c16), np.asarray(c32),
                                   atol=2.0)
        # bf16 GEMM inputs, f32 accumulation: ~1e-2 relative (atol covers
        # the one boundary point moving between cluster sums)
        np.testing.assert_allclose(np.asarray(s16), np.asarray(s32),
                                   rtol=2e-2, atol=12.0)
        np.testing.assert_allclose(float(i16), float(i32), rtol=2e-2)

    def test_outputs_stay_float32(self):
        X, _ = make_blobs(n_samples=64, centers=2, n_features=8,
                          cluster_std=0.5, random_state=3)
        Xd = jnp.asarray(X)
        out = lloyd_step_pallas(Xd, jnp.ones(64, jnp.float32), Xd[:2],
                                row_norms(Xd, squared=True), interpret=True,
                                compute_dtype="bfloat16")
        labels, mind2, sums, counts, inertia = out
        assert labels.dtype == jnp.int32
        for a in (mind2, sums, counts, inertia):
            assert a.dtype == jnp.float32


class TestArgkminKernel:
    """Fused k-nearest search (the TPU twin of native.argkmin; reference
    role: neighbors/_ball_tree.pyx). Interpreter mode on CPU."""

    @pytest.mark.parametrize("nt,nq,m,k", [
        (1000, 300, 17, 5),   # deliberately unaligned everything
        (513, 90, 8, 1),      # k=1, odd train count
        (300, 50, 4, 13),     # k > lane-tile fraction, tiny features
    ])
    def test_matches_xla_knn_indices(self, nt, nq, m, k):
        from sq_learn_tpu.models.neighbors import knn_indices
        from sq_learn_tpu.ops.pallas_kernels import argkmin_pallas

        rng = np.random.RandomState(3)
        Xt = jnp.asarray(rng.randn(nt, m).astype(np.float32))
        Xq = jnp.asarray(rng.randn(nq, m).astype(np.float32))
        xsq = jnp.sum(Xt * Xt, axis=1)
        idx_p, d2_p = argkmin_pallas(Xt, xsq, Xq, k, tile_q=64,
                                     tile_t=128, interpret=True)
        idx_x, d2_x = knn_indices(Xt, Xq, k)
        np.testing.assert_array_equal(np.asarray(idx_p), np.asarray(idx_x))
        np.testing.assert_allclose(np.asarray(d2_p), np.asarray(d2_x),
                                   rtol=1e-4, atol=1e-4)
        # ascending output contract
        assert (np.diff(np.asarray(d2_p), axis=1) >= -1e-6).all()

    def test_k_bounds_validated(self):
        from sq_learn_tpu.ops.pallas_kernels import argkmin_pallas

        X = jnp.ones((10, 4), jnp.float32)
        with pytest.raises(ValueError, match="outside"):
            argkmin_pallas(X, jnp.sum(X * X, 1), X, 11, interpret=True)

    def test_classifier_end_to_end(self):
        """KNeighborsClassifier(use_pallas=True) predicts identically to
        the XLA path (host fast path defeated: it would win the dispatch
        on the CPU backend before the device search is consulted)."""
        from sq_learn_tpu.models.neighbors import KNeighborsClassifier

        X, y = make_blobs(n_samples=400, centers=3, n_features=12,
                          cluster_std=2.0, random_state=9)
        Xtr, ytr, Xte = X[:300], y[:300], X[300:]
        preds = {}
        for up in (False, True):
            est = KNeighborsClassifier(n_neighbors=7, weights="distance",
                                       use_pallas=up).fit(Xtr, ytr)
            est._host_search = lambda X, k: None
            preds[up] = est.predict(Xte)
        np.testing.assert_array_equal(preds[True], preds[False])


class TestEstimatorIntegration:
    def test_kmeans_pallas_matches_xla(self):
        X, y = make_blobs(n_samples=300, centers=4, n_features=6,
                          cluster_std=0.6, random_state=5)
        init = X[:4].copy()
        km_x = KMeans(n_clusters=4, init=init, n_init=1, random_state=0,
                      use_pallas=False).fit(X)
        km_p = KMeans(n_clusters=4, init=init, n_init=1, random_state=0,
                      use_pallas=True).fit(X)
        np.testing.assert_array_equal(km_x.labels_, km_p.labels_)
        np.testing.assert_allclose(km_x.cluster_centers_,
                                   km_p.cluster_centers_, rtol=1e-4,
                                   atol=1e-4)
        np.testing.assert_allclose(km_x.inertia_, km_p.inertia_, rtol=1e-4)


def test_lloyd_step_pallas_delta_mode_interpret():
    """δ-means fused kernel: labels stay inside the δ-window of the min,
    partials are consistent with the sampled labels, inertia still uses
    the true min distances."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sq_learn_tpu.datasets import make_blobs
    from sq_learn_tpu.ops.linalg import pairwise_sq_distances, row_norms
    from sq_learn_tpu.ops.pallas_kernels import lloyd_step_pallas

    X, _ = make_blobs(n_samples=300, centers=4, n_features=8,
                      cluster_std=1.5, random_state=1)
    X = jnp.asarray(X)
    w = jnp.ones(300, X.dtype)
    centers = X[:4]
    xsq = row_norms(X, squared=True)
    delta = 5.0

    labels, mind2, sums, counts, inertia = lloyd_step_pallas(
        X, w, centers, xsq, key=jax.random.PRNGKey(0), window=delta,
        interpret=True)

    d2 = np.asarray(pairwise_sq_distances(X, centers, xsq))
    min_d2 = d2.min(axis=1)
    labels = np.asarray(labels)
    # every sampled label is within the δ-window of its row minimum
    sel = d2[np.arange(300), labels]
    assert (sel <= min_d2 + delta + 1e-4).all()
    # with a wide window some rows must deviate from pure argmin
    assert (labels != d2.argmin(axis=1)).any()
    # partials consistent with the sampled labels; inertia from true mins
    assert float(counts.sum()) == pytest.approx(300.0)
    for j in range(4):
        np.testing.assert_allclose(np.asarray(sums)[j],
                                   np.asarray(X)[labels == j].sum(0),
                                   rtol=1e-4, atol=1e-4)
    assert float(inertia) == pytest.approx(float(min_d2.sum()), rel=1e-5)


def test_lloyd_single_fused_bf16_quality():
    """A reduced compute_dtype now rides the fused pallas kernel (bf16
    MXU blocks) instead of falling back to XLA; clustering quality must
    be unchanged on resolvable separations."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sq_learn_tpu.datasets import make_blobs
    from sq_learn_tpu.metrics import adjusted_rand_score
    from sq_learn_tpu.models.qkmeans import lloyd_single
    from sq_learn_tpu.ops.linalg import row_norms

    X, y = make_blobs(n_samples=300, centers=4, n_features=8,
                      cluster_std=0.5, random_state=4)
    Xd = jnp.asarray(X - X.mean(0))
    w = jnp.ones(300, Xd.dtype)
    xsq = row_norms(Xd, squared=True)
    centers0 = Xd[np.asarray([5, 80, 160, 240])]
    labels, inertia, centers, n_iter, _ = lloyd_single(
        jax.random.PRNGKey(0), Xd, w, centers0, xsq, mode="classic",
        max_iter=50, use_pallas=True, pallas_interpret=True,
        compute_dtype="bfloat16")
    assert adjusted_rand_score(y, np.asarray(labels)) > 0.95
    assert np.isfinite(float(inertia))


def test_lloyd_single_fused_delta_matches_quality():
    """Full fused δ-means run (interpret mode) clusters blobs correctly."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sq_learn_tpu.datasets import make_blobs
    from sq_learn_tpu.metrics import adjusted_rand_score
    from sq_learn_tpu.models.qkmeans import lloyd_single
    from sq_learn_tpu.ops.linalg import row_norms

    X, y = make_blobs(n_samples=300, centers=4, n_features=8,
                      cluster_std=0.5, random_state=2)
    Xd = jnp.asarray(X - X.mean(0))
    w = jnp.ones(300, Xd.dtype)
    xsq = row_norms(Xd, squared=True)
    centers0 = Xd[np.asarray([5, 80, 160, 240])]
    labels, inertia, centers, n_iter, history = lloyd_single(
        jax.random.PRNGKey(0), Xd, w, centers0, xsq, delta=0.5,
        mode="delta", max_iter=50, use_pallas=True, pallas_interpret=True)
    assert adjusted_rand_score(y, np.asarray(labels)) > 0.95


@pytest.mark.slow
def test_argkmin_fuzz_matches_top_k():
    """Randomized shape/k sweep incl. duplicate training rows (tie
    stress): the fused argkmin must match the XLA top_k path's indices
    EXACTLY — the lane-aligned merge rewrite keeps the same tie order."""
    from sq_learn_tpu.models.neighbors import knn_indices
    from sq_learn_tpu.ops.pallas_kernels import argkmin_pallas

    rng = np.random.default_rng(0)
    for _ in range(10):
        nt = int(rng.integers(5, 900))
        nq = int(rng.integers(1, 400))
        m = int(rng.integers(1, 70))
        k = int(rng.integers(1, min(nt, 20) + 1))
        Xt = rng.standard_normal((nt, m)).astype(np.float32)
        Xq = rng.standard_normal((nq, m)).astype(np.float32)
        if nt > 10:  # duplicates exercise the lowest-index tie contract
            Xt[nt // 2] = Xt[0]
            Xt[-1] = Xt[1]
        xsq = (Xt ** 2).sum(1)
        pi, pd = argkmin_pallas(jnp.asarray(Xt), jnp.asarray(xsq),
                                jnp.asarray(Xq), k, interpret=True)
        xi, xd = knn_indices(jnp.asarray(Xt), jnp.asarray(Xq), k)
        np.testing.assert_array_equal(np.asarray(pi), np.asarray(xi))
        np.testing.assert_allclose(np.asarray(pd), np.asarray(xd),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_lloyd_fuzz_matches_xla_across_lane_boundary(key):
    """Randomized (n, m, k) sweep with k crossing the 128-lane padding
    boundary: fused-kernel labels match the XLA E-step exactly, weighted
    partials match the one-hot GEMM."""
    from sq_learn_tpu.models.qkmeans import _cluster_partials, e_step

    rng = np.random.default_rng(1)
    for _ in range(8):
        n = int(rng.integers(10, 1500))
        m = int(rng.integers(1, 150))
        k = int(rng.integers(2, 200))
        X = rng.standard_normal((n, m)).astype(np.float32)
        w = rng.uniform(0.2, 2.0, n).astype(np.float32)
        C = X[rng.choice(n, min(k, n), replace=False)]
        k = C.shape[0]
        Xd, wd, Cd = jnp.asarray(X), jnp.asarray(w), jnp.asarray(C)
        xsq = jnp.sum(Xd * Xd, axis=1)
        lab, _, sums, counts, inert = lloyd_step_pallas(
            Xd, wd, Cd, xsq, interpret=True)
        rl, ri, _ = e_step(key, Xd, wd, Cd, xsq, delta=0.0,
                           mode="classic", ipe_q=1)
        rs, rc = _cluster_partials(Xd, wd, rl, k)
        np.testing.assert_array_equal(np.asarray(lab), np.asarray(rl))
        np.testing.assert_allclose(np.asarray(sums), np.asarray(rs),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(counts), np.asarray(rc),
                                   rtol=1e-5)
        np.testing.assert_allclose(float(inert), float(ri), rtol=1e-4)


class TestKernelRejectionMemoization:
    """The process-global rejection caches (VERDICT r4 next #5): a
    structural Mosaic/lowering failure is learned once per (backend,
    shape-family) signature; transient failures (OOM, tunnel resets) and
    explicit ``use_pallas=True`` overrides never poison the caches."""

    def test_memoizable_failure_classification(self):
        from sq_learn_tpu.models.qkmeans import _memoizable_kernel_failure

        # structural: lowering/compile rejections the backend will repeat
        assert _memoizable_kernel_failure(NotImplementedError("no"))
        assert _memoizable_kernel_failure(
            RuntimeError("Mosaic lowering failed: op not supported"))
        assert _memoizable_kernel_failure(
            ValueError("UNIMPLEMENTED: dynamic slice on minor dim"))
        # transient: must retry on the next fit/predict
        assert not _memoizable_kernel_failure(
            RuntimeError("RESOURCE_EXHAUSTED: out of memory while trying "
                         "to allocate 1.2G"))
        assert not _memoizable_kernel_failure(
            RuntimeError("connection reset by peer"))
        # an OOM whose message also names the compiler stays transient:
        # the early RESOURCE_EXHAUSTED check wins over the MOSAIC keyword
        assert not _memoizable_kernel_failure(
            RuntimeError("RESOURCE_EXHAUSTED: mosaic kernel arena"))

    @staticmethod
    def _fit_knn(k=3, n=40, m=16, use_pallas="auto"):
        from sq_learn_tpu.models.neighbors import KNeighborsClassifier

        rng = np.random.default_rng(0)
        X = rng.normal(size=(n, m)).astype(np.float32)
        y = np.asarray(rng.integers(0, 3, n))
        knn = KNeighborsClassifier(n_neighbors=k,
                                   use_pallas=use_pallas).fit(X, y)
        return knn, X[:5]

    @staticmethod
    def _patch_argkmin(monkeypatch, message):
        """Replace the pallas argkmin with a raiser; returns the call log."""
        from sq_learn_tpu.models import neighbors as nbr
        from sq_learn_tpu.ops import pallas_kernels as pk

        monkeypatch.setattr(nbr, "_argkmin_rejected", set())
        calls = []

        def fake_argkmin(Xtr, xsq, Xq, k, interpret=False):
            calls.append(k)
            raise RuntimeError(message)

        monkeypatch.setattr(pk, "argkmin_pallas", fake_argkmin)
        monkeypatch.setattr(pk, "pallas_available", lambda: True)
        return calls

    def test_structural_rejection_cached_once_per_signature(self, monkeypatch):
        import warnings

        from sq_learn_tpu.models import neighbors as nbr

        calls = self._patch_argkmin(
            monkeypatch, "Mosaic lowering failed: unsupported op")
        knn, Xq = self._fit_knn()
        with pytest.warns(UserWarning, match="falling back to the XLA"):
            knn._device_search(Xq, 3)
        assert calls == [3]
        assert len(nbr._argkmin_rejected) == 1
        # second call skips the pallas trace entirely — no retry, no warning
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            idx, d2 = knn._device_search(Xq, 3)
        assert calls == [3]
        assert idx.shape == (5, 3)  # XLA fallback still answers
        # a different k is a different kernel shape family: not blacklisted
        with pytest.warns(UserWarning, match="falling back to the XLA"):
            knn._device_search(Xq, 2)
        assert calls == [3, 2]

    def test_explicit_use_pallas_true_bypasses_and_never_blacklists(
            self, monkeypatch):
        from sq_learn_tpu.models import neighbors as nbr

        calls = self._patch_argkmin(
            monkeypatch, "Mosaic lowering failed: unsupported op")
        knn, Xq = self._fit_knn(use_pallas=True)
        for _ in range(2):  # keeps retrying on every call (user override)
            with pytest.warns(UserWarning, match="falling back to the XLA"):
                knn._device_search(Xq, 3)
        assert calls == [3, 3]
        assert nbr._argkmin_rejected == set()
        # ...and the explicit failures did not disable the auto path
        auto_knn, _ = self._fit_knn(use_pallas="auto")
        with pytest.warns(UserWarning, match="falling back to the XLA"):
            auto_knn._device_search(Xq, 3)
        assert calls == [3, 3, 3]

    def test_transient_oom_not_blacklisted(self, monkeypatch):
        from sq_learn_tpu.models import neighbors as nbr

        calls = self._patch_argkmin(
            monkeypatch, "RESOURCE_EXHAUSTED: out of memory in VMEM")
        knn, Xq = self._fit_knn()
        for _ in range(2):  # both calls attempt the kernel again
            with pytest.warns(UserWarning, match="falling back to the XLA"):
                knn._device_search(Xq, 3)
        assert calls == [3, 3]
        assert nbr._argkmin_rejected == set()

    def test_kernel_ladder_memoizes_structural_per_signature(
            self, monkeypatch):
        import warnings

        from sq_learn_tpu.models import qkmeans as qk

        monkeypatch.setattr(qk, "_failed_kernels", set())
        est = qk.QKMeans(n_clusters=2)
        calls = []

        def run(up, itp):
            calls.append((up, itp))
            if up:
                raise NotImplementedError("mosaic says no")
            return "ok"

        with pytest.warns(RuntimeWarning, match="retrying without"):
            out = est._kernel_ladder("lloyd", True, False, run, "giving up.",
                                     sig=(5, 17))
        assert out == "ok" and calls == [(True, False), (False, False)]
        # second fit with the same signature: the rejected kernel is
        # skipped up front (no re-trace, no warning)
        calls.clear()
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            out = est._kernel_ladder("lloyd", True, False, run, "giving up.",
                                     sig=(5, 17))
        assert out == "ok" and calls == [(False, False)]
        # a different operand signature re-learns the kernel independently
        calls.clear()
        with pytest.warns(RuntimeWarning, match="retrying without"):
            est._kernel_ladder("lloyd", True, False, run, "giving up.",
                               sig=(7, 3))
        assert calls == [(True, False), (False, False)]

    def test_kernel_ladder_transient_failures_retried(self, monkeypatch):
        from sq_learn_tpu.models import qkmeans as qk

        monkeypatch.setattr(qk, "_failed_kernels", set())
        est = qk.QKMeans(n_clusters=2)
        calls = []

        def run(up, itp):
            calls.append((up, itp))
            if up:
                raise RuntimeError("RESOURCE_EXHAUSTED: 2G on one operand")
            return "ok"

        for _ in range(2):
            with pytest.warns(RuntimeWarning, match="retrying without"):
                est._kernel_ladder("lloyd", True, False, run, "giving up.",
                                   sig=(5, 17))
        # the pallas plan was attempted both times — OOM is not structural
        assert calls == [(True, False), (False, False)] * 2
        assert qk._failed_kernels == set()
