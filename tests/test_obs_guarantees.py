"""Guarantee auditor (sq_learn_tpu.obs.guarantees): the statistical
observability contract of ISSUE 5 — every simulated routine's realized
error audited against its declared (ε, δ), flagged only on
Clopper–Pearson statistical inconsistency, with δ=0/ε=0 short-circuits
recording zero violations by construction."""

import math

import numpy as np
import jax
import pytest

from sq_learn_tpu import obs
from sq_learn_tpu.obs import guarantees
from sq_learn_tpu.obs.schema import validate_record


@pytest.fixture
def run():
    rec = obs.enable()
    yield rec
    obs.disable()


# -- Clopper–Pearson core ----------------------------------------------------


class TestClopperPearson:
    def test_zero_violations_bound_is_zero(self):
        assert guarantees.clopper_pearson_lower(0, 50) == 0.0
        assert guarantees.clopper_pearson_lower(0, 0) == 0.0

    def test_all_violations_known_value(self):
        # P(X >= n | p) = p^n = alpha  =>  p = alpha^(1/n)
        lcb = guarantees.clopper_pearson_lower(10, 10, confidence=0.95)
        assert lcb == pytest.approx(0.05 ** 0.1, abs=1e-6)

    def test_monotone_in_violations(self):
        bounds = [guarantees.clopper_pearson_lower(k, 100)
                  for k in (1, 5, 20, 80)]
        assert bounds == sorted(bounds)
        assert all(0.0 < b < 1.0 for b in bounds)

    def test_single_draw_never_alarms(self):
        """The no-flaky-alarms property: ONE violated draw against any
        plausible declared δ cannot flag — the lower bound on 1/1 at
        95 % is 5 %, so only contracts declaring fail_prob < 5 % could
        even in principle flag on a single draw, and 1/n for n ≥ 2
        drops fast."""
        assert guarantees.clopper_pearson_lower(1, 1) == \
            pytest.approx(0.05, abs=1e-6)
        assert guarantees.clopper_pearson_lower(1, 20) < 0.01


# -- record / audit mechanics ------------------------------------------------


class TestRecords:
    def test_disabled_is_noop(self):
        obs.disable()
        guarantees.record_guarantee("s", 0.5, 0.1)
        guarantees.observe("s", [1.0], 0.1)
        assert guarantees.audit() == {}

    def test_records_are_schema_valid(self, run):
        guarantees.record_guarantee("site.a", 0.05, 0.1, fail_prob=0.1)
        guarantees.record_guarantee("site.a", 0.2, 0.1, fail_prob=0.1,
                                    n_total=100)
        guarantees.record_guarantee("site.b", 0.0, 0.0, fail_prob=0.0,
                                    short_circuit=True)
        for rec in run.guarantee_records:
            assert validate_record(rec) == [], rec
        a = guarantees.audit()
        assert a["site.a"]["trials"] == 2
        assert a["site.a"]["violations"] == 1
        assert a["site.b"]["short_circuits"] == 1
        assert a["site.b"]["violations"] == 0

    def test_batch_subsampling_caps_records(self, run):
        guarantees.observe("big", np.zeros(10_000), 1.0, fail_prob=0.1)
        n = len(run.guarantee_records)
        assert n <= guarantees._MAX_DRAWS_PER_CALL
        assert all(r["n_total"] == 10_000 for r in run.guarantee_records)

    def test_audit_uses_loosest_declared_fail_prob(self, run):
        guarantees.record_guarantee("s", 0.2, 0.1, fail_prob=0.01)
        guarantees.record_guarantee("s", 0.0, 0.1, fail_prob=0.3)
        assert guarantees.audit()["s"]["fail_prob"] == 0.3

    def test_snapshot_carries_audit_view(self, run):
        guarantees.record_guarantee("s", 0.2, 0.1, fail_prob=0.5)
        snap = obs.snapshot()
        assert snap["guarantee_records"] == 1
        assert snap["guarantee_violations"] == 1
        assert snap["audit_flagged"] == []
        assert "tradeoff_records" in snap


# -- the three acceptance behaviors (ISSUE 5) --------------------------------


class TestAcceptance:
    def test_correct_routine_passes_at_declared_delta(self, run):
        """(a) a correctly-budgeted amplitude estimation passes the audit:
        200 draws at the derived grid size M(ε) with γ-boosting stay
        within ε essentially always, so the site is not flagged."""
        from sq_learn_tpu.ops.quantum.estimation import amplitude_estimation

        a = np.linspace(0.05, 0.95, 200)
        amplitude_estimation(jax.random.PRNGKey(0), a, epsilon=0.01,
                             gamma=0.05)
        summary = guarantees.audit()["amplitude_estimation"]
        assert summary["trials"] > 0
        assert not summary["flagged"]
        assert summary["lower_bound"] <= summary["fail_prob"]

    def test_under_budgeted_routine_is_flagged(self, run):
        """(b) an under-budgeted routine — grid M=8 against a declared
        ε=0.001 — violates its tolerance on most draws, and the
        Clopper–Pearson lower bound crosses the declared γ."""
        from sq_learn_tpu.ops.quantum.estimation import amplitude_estimation

        a = np.linspace(0.05, 0.95, 200)
        amplitude_estimation(jax.random.PRNGKey(1), a, epsilon=0.001,
                             gamma=0.05, M=8)
        summary = guarantees.audit()["amplitude_estimation"]
        assert summary["violations"] > 0
        assert summary["flagged"]
        assert summary["lower_bound"] > summary["fail_prob"]

    def test_zero_budget_short_circuits_record_zero_violations(self, run):
        """(c) δ=0/ε=0 short-circuits are exact classical computations:
        the guarantee records say so by construction — zero realized
        error, zero violations, short_circuit flagged."""
        from sq_learn_tpu.ops.quantum.tomography import tomography

        A = np.random.default_rng(0).normal(size=(6, 16)).astype(np.float32)
        out = tomography(jax.random.PRNGKey(2), A, 0.0)
        np.testing.assert_array_equal(np.asarray(out), A)
        recs = [r for r in run.guarantee_records
                if r["site"] == "tomography.true"]
        assert recs and all(r.get("short_circuit") for r in recs)
        assert all(not r["violated"] and r["realized"] == 0.0
                   for r in recs)
        summary = guarantees.audit()["tomography.true"]
        assert summary["violations"] == 0 and not summary["flagged"]

    def test_strict_mode_raises_on_flagged_site(self, run, monkeypatch):
        monkeypatch.setenv("SQ_OBS_AUDIT_STRICT", "1")
        from sq_learn_tpu.ops.quantum.estimation import amplitude_estimation

        with pytest.raises(guarantees.GuaranteeViolationError):
            # enough grossly-under-budgeted draws to cross any bound
            amplitude_estimation(jax.random.PRNGKey(3),
                                 np.linspace(0.1, 0.9, 200),
                                 epsilon=1e-5, gamma=0.01, M=4)

    def test_strict_mode_tolerates_probabilistic_violations(self, run,
                                                            monkeypatch):
        """A single violated draw under a loose declared γ must NOT raise
        — the whole point of the confidence bound."""
        monkeypatch.setenv("SQ_OBS_AUDIT_STRICT", "1")
        guarantees.record_guarantee("loose", 0.2, 0.1, fail_prob=0.5)
        guarantees.record_guarantee("loose", 0.05, 0.1, fail_prob=0.5)
        assert not guarantees.audit()["loose"]["flagged"]


# -- instrumented routines ---------------------------------------------------


class TestRoutineInstrumentation:
    def test_tomography_true_rows_within_delta(self, run, key):
        from sq_learn_tpu.ops.quantum.tomography import tomography

        A = np.random.default_rng(1).normal(size=(5, 32)).astype(np.float32)
        tomography(key, A, 0.4)
        recs = [r for r in run.guarantee_records
                if r["site"] == "tomography.true"]
        assert len(recs) == 5
        assert all(r["tol"] == pytest.approx(0.4) for r in recs)
        assert all(validate_record(r) == [] for r in recs)

    def test_tomography_gaussian_bounded_by_construction(self, run, key):
        from sq_learn_tpu.ops.quantum.tomography import tomography

        A = np.random.default_rng(2).normal(size=(8, 16)).astype(np.float32)
        tomography(key, A, 0.7, true_tomography=False)
        recs = [r for r in run.guarantee_records
                if r["site"] == "tomography.gaussian"]
        assert len(recs) == 1  # one flattened-matrix draw
        assert recs[0]["fail_prob"] == 0.0
        assert not recs[0]["violated"]

    def test_traced_calls_are_not_audited(self, run, key):
        from sq_learn_tpu.ops.quantum.estimation import amplitude_estimation

        jax.jit(lambda k, a: amplitude_estimation(k, a, epsilon=0.1))(
            key, 0.3)
        assert run.guarantee_records == []

    def test_consistent_pe_and_ipe_sites(self, run, key):
        from sq_learn_tpu.ops.quantum.estimation import (
            consistent_phase_estimation, inner_product_estimates)

        consistent_phase_estimation(
            key, np.linspace(0.1, 0.4, 16), epsilon=0.05, gamma=0.1)
        rng = np.random.default_rng(3)
        X = rng.normal(size=(32, 8)).astype(np.float32)
        C = rng.normal(size=(4, 8)).astype(np.float32)
        inner_product_estimates(key, X, C, epsilon=0.25)
        sites = {r["site"] for r in run.guarantee_records}
        assert {"consistent_phase_estimation", "phase_estimation",
                "ipe"} <= sites
        flagged = [s for s, a in guarantees.audit().items() if a["flagged"]]
        assert flagged == []

    def test_qkmeans_fit_audit_delta_window(self, run):
        from sq_learn_tpu.models import QKMeans

        rng = np.random.default_rng(4)
        X = np.concatenate([rng.normal(loc=c, size=(40, 6))
                            for c in (-4, 0, 4)]).astype(np.float32)
        QKMeans(n_clusters=3, n_init=1, delta=0.5,
                true_distance_estimate=False, random_state=0).fit(X)
        recs = [r for r in run.guarantee_records
                if r["site"] == "qkmeans.delta_window"]
        assert recs
        # the δ-window rule satisfies its own contract by construction
        assert all(not r["violated"] for r in recs)

    def test_qkmeans_classic_fit_short_circuits(self, run):
        import warnings

        from sq_learn_tpu.models import QKMeans

        X = np.random.default_rng(5).normal(size=(60, 5)).astype(np.float32)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            QKMeans(n_clusters=3, n_init=1, delta=0.0,
                    random_state=0).fit(X)
        recs = [r for r in run.guarantee_records
                if r["site"] == "qkmeans.delta_window"]
        assert recs and all(r.get("short_circuit") for r in recs)
        assert guarantees.audit()["qkmeans.delta_window"]["violations"] == 0

    def test_qlssvc_predict_audits_noise_model(self, run):
        from sq_learn_tpu.models import QLSSVC

        rng = np.random.default_rng(6)
        X = rng.normal(size=(40, 4))
        y = np.where(X[:, 0] > 0, 1.0, -1.0)
        clf = QLSSVC(absolute_error=0.05, random_state=0).fit(X, y)
        clf.predict(X[:10])
        recs = [r for r in run.guarantee_records
                if r["site"] == "qlssvc.noisy_p"]
        assert recs
        assert all(not r["violated"] for r in recs)


# -- CLI / render ------------------------------------------------------------


class TestCLI:
    def test_audit_cli_green_and_flagged(self, tmp_path, capsys):
        import json

        path = str(tmp_path / "g.jsonl")
        with open(path, "w") as fh:
            for i in range(30):
                fh.write(json.dumps(
                    {"v": 3, "schema_version": 3, "ts": 0.0,
                     "type": "guarantee", "site": "bad", "realized": 1.0,
                     "tol": 0.1, "violated": True,
                     "fail_prob": 0.05}) + "\n")
            fh.write(json.dumps(
                {"v": 3, "schema_version": 3, "ts": 0.0,
                 "type": "guarantee", "site": "good", "realized": 0.01,
                 "tol": 0.1, "violated": False, "fail_prob": 0.05}) + "\n")
        assert guarantees.main([path]) == 1
        out = capsys.readouterr().out
        assert "bad" in out and "FLAGGED" in out

    def test_report_includes_audit_section(self, tmp_path, capsys):
        from sq_learn_tpu.obs import report

        path = str(tmp_path / "r.jsonl")
        obs.enable(path)
        try:
            guarantees.record_guarantee("s", 0.01, 0.1, fail_prob=0.1)
        finally:
            obs.disable()
        assert report.main([path]) == 0
        out = capsys.readouterr().out
        assert "guarantee audit" in out
        assert "0/1" in out.replace(" ", "")[:10_000] or "s" in out

    def test_log_binom_tail_sane(self):
        # P(X >= 1 | n=10, p=0.1) = 1 - 0.9^10
        got = math.exp(guarantees._log_binom_tail_geq(10, 1, 0.1))
        assert got == pytest.approx(1 - 0.9 ** 10, rel=1e-9)
