"""Resilience layer (sq_learn_tpu.resilience): deterministic fault
injection, the supervised transfer path (retry/backoff/deadline), the
probe-fed circuit breaker, and resumable streaming passes — ISSUE 3's
contract.

Parity discipline: a fault-injected-and-recovered (or
interrupted-and-resumed) streamed computation must agree with the
fault-free one BIT-FOR-BIT — recovery re-runs the same kernels over the
same tiles in the same order, and the checkpoint's npz round-trip is
lossless, so tolerance here would hide a real divergence.
"""

import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from sq_learn_tpu import obs, streaming
from sq_learn_tpu.obs import probe as probe_mod
from sq_learn_tpu.obs.schema import validate_record
from sq_learn_tpu.resilience import faults, supervisor
from sq_learn_tpu.resilience.faults import (FaultSpecError, InjectedFault,
                                            InjectedInterrupt,
                                            InjectedTransferError)
from sq_learn_tpu.resilience.supervisor import (CLOSED, HALF_OPEN, OPEN,
                                                CircuitBreaker,
                                                NonFiniteAccumulatorError)

RNG = np.random.default_rng(0)
# 1003 rows / 150-row tiles: 7 tiles with a ragged tail (same shape
# discipline as test_streaming)
X_TALL = (RNG.normal(size=(1003, 16)) + 2.0).astype(np.float32)
ROW_BYTES = X_TALL.nbytes // X_TALL.shape[0]
TILE_BYTES = 150 * ROW_BYTES


@pytest.fixture(autouse=True)
def _fresh_resilience_state(monkeypatch):
    """Every test starts disarmed with a closed, history-free breaker and
    fast retries; probe caching is scoped away from the shared /tmp
    file so tests can neither read nor leave cross-process state."""
    monkeypatch.setenv("SQ_RETRY_BACKOFF_S", "0.001")
    monkeypatch.setenv("SQ_PROBE_CACHE", "/dev/null/nonexistent")
    monkeypatch.setattr(probe_mod, "last_probe", None)
    monkeypatch.setattr(probe_mod, "_last_probe_t", None)
    yield
    faults.disarm()
    br = supervisor.breaker
    br.trip_action = supervisor._cpu_escape
    br.reset()
    br.transitions.clear()
    br.trips = 0


# -- fault spec grammar ------------------------------------------------------


class TestFaultSpec:
    def test_parse_multi_clause(self):
        plan = faults.FaultPlan(
            "put_fail:tiles=2/5,times=2;put_stall:p=0.5,s=0.1,seed=7;"
            "nan:tiles=1;abort:tile=4;probe_timeout:n=3")
        kinds = [inj.kind for inj in plan.injectors]
        assert kinds == ["put_fail", "put_stall", "nan", "abort",
                         "probe_timeout"]
        assert plan.injectors[0].tiles == {2, 5}
        assert plan.injectors[0].times == 2
        assert plan.injectors[1].p == 0.5 and plan.injectors[1].seed == 7
        assert plan.injectors[3].tile == 4
        assert plan.injectors[4].count == 3

    @pytest.mark.parametrize("bad", [
        "", "wedge_everything", "put_fail:frequency=2",
        "put_fail:tiles", "put_stall:s=often"])
    def test_malformed_specs_raise(self, bad):
        with pytest.raises(FaultSpecError):
            faults.parse_spec(bad)

    def test_arm_disarm_roundtrip(self):
        assert not faults.active()
        plan = faults.arm("put_fail:tiles=0")
        assert faults.active() and faults.get_plan() is plan
        assert faults.disarm() is plan
        assert not faults.active()

    def test_nan_injection_skips_integer_tiles(self):
        """NaN cannot be assigned into an integer tile — a selected
        non-float tile must pass through unmodified with a skipped-event
        record, not crash the supervised put from inside the harness."""
        plan = faults.FaultPlan("nan:tiles=0/1")
        int_tile = np.arange(6, dtype=np.int32).reshape(2, 3)
        out = plan.corrupt(int_tile, 0)
        np.testing.assert_array_equal(out, int_tile)
        float_tile = np.ones((2, 3), np.float32)
        poisoned = plan.corrupt(float_tile, 1)
        assert np.isnan(poisoned).any()
        assert np.isfinite(float_tile).all()  # original untouched
        assert [ev.get("skipped") for ev in plan.events] == [
            "non-float dtype", None]

    def test_probabilistic_selection_is_deterministic(self):
        picks = [
            [t for t in range(64)
             if faults.FaultPlan("nan:p=0.25,seed=3").injectors[0].matches(t)]
            for _ in range(2)]
        assert picks[0] == picks[1]
        assert 4 < len(picks[0]) < 28  # ~16 expected of 64


# -- zero-overhead no-op path ------------------------------------------------


class TestDisabledOverhead:
    def test_unarmed_hooks_are_single_attribute_reads(self):
        assert faults._active is None
        assert supervisor.breaker._state == CLOSED

    def test_supervised_put_fast_path_micro(self):
        """SQ_FAULTS off + closed breaker: the supervised put is a timed
        raw call — pinned like the obs recorder's disabled overhead
        (~1 µs/op would already be far above the observed cost; the
        bound is loose against host noise)."""
        tile = np.zeros(4, np.float32)
        n = 100_000
        t0 = time.perf_counter()
        for _ in range(n):
            supervisor.put(lambda t: t, tile)
        elapsed = time.perf_counter() - t0
        assert elapsed < 2.0, f"supervised-put overhead too high: {elapsed:.3f}s"


# -- retry / backoff ---------------------------------------------------------


class TestRetry:
    def test_transient_failure_recovers_with_parity(self):
        mean_ref, Gc_ref, _ = streaming.streamed_centered_gram(
            X_TALL, max_bytes=TILE_BYTES)
        plan = faults.arm("put_fail:tiles=2,times=2")
        mean_f, Gc_f, _ = streaming.streamed_centered_gram(
            X_TALL, max_bytes=TILE_BYTES)
        assert [ev["kind"] for ev in plan.events] == ["put_fail", "put_fail"]
        # recovery is a re-put of the same tile: results are bit-identical
        np.testing.assert_array_equal(np.asarray(Gc_f), np.asarray(Gc_ref))
        np.testing.assert_array_equal(np.asarray(mean_f),
                                      np.asarray(mean_ref))
        assert supervisor.breaker.state() == CLOSED
        assert supervisor.breaker.consecutive_failures == 0

    @pytest.mark.parametrize("exc_type", [RuntimeError, OSError,
                                          jax.errors.JaxRuntimeError])
    def test_fast_path_retries_real_transient_errors(self, exc_type):
        """No faults armed, breaker closed — the normal production state:
        a REAL transient backend error out of the raw put must retry and
        feed the breaker, not propagate after a single attempt (the
        relay-wedge scenario this layer exists for does not set
        SQ_FAULTS)."""
        assert faults._active is None
        assert supervisor.breaker._state == CLOSED
        calls = []

        def flaky(t):
            calls.append(1)
            if len(calls) < 3:
                raise exc_type("transient relay hiccup")
            return t

        out = supervisor.put(flaky, np.ones(4, np.float32))
        assert len(calls) == 3
        np.testing.assert_array_equal(np.asarray(out),
                                      np.ones(4, np.float32))
        # the final success reset the consecutive count the two real
        # failures had built up
        assert supervisor.breaker.consecutive_failures == 0

    def test_fast_path_failures_feed_the_breaker(self, monkeypatch):
        monkeypatch.setenv("SQ_BREAKER_K", "2")
        trips = []
        supervisor.breaker.trip_action = lambda: trips.append(True)
        calls = []

        def flaky(t):
            calls.append(1)
            if len(calls) < 3:
                raise OSError("connection reset by relay")
            return t

        supervisor.put(flaky, np.ones(2, np.float32))
        # two consecutive real failures tripped at K=2, mid-retry
        assert len(calls) == 3 and trips == [True]
        assert supervisor.breaker.state() == OPEN

    @pytest.mark.parametrize("armed", [False, True])
    @pytest.mark.parametrize("exc", [
        ValueError("operand shapes incompatible"),
        TypeError("unhashable sharding"),
        RuntimeError("RESOURCE_EXHAUSTED: Out of memory while trying to "
                     "allocate 2147483648 bytes"),
        NonFiniteAccumulatorError("non-finite accumulator leaf 0"),
        InjectedInterrupt("injected mid-pass interrupt"),
    ])
    def test_deterministic_errors_never_retry(self, exc, armed):
        """Shape/dtype mistakes, XLA OOM, and package-internal control
        flow recur on every attempt: one call, no breaker feeding, no
        backoff sleeps — K of them must never repin the process to CPU
        (the trip action is process-global)."""
        if armed:
            faults.arm("probe_timeout:n=1")  # forces the supervised path
        calls = []

        def broken(t):
            calls.append(1)
            raise exc

        with pytest.raises(type(exc)):
            supervisor.put(broken, np.ones(2, np.float32))
        assert len(calls) == 1
        assert supervisor.breaker.consecutive_failures == 0

    def test_retries_exhausted_raises_terminal(self, monkeypatch):
        monkeypatch.setenv("SQ_RETRY_MAX", "2")
        monkeypatch.setenv("SQ_BREAKER_K", "99")  # keep it from tripping
        faults.arm("put_fail:tiles=0,times=10")
        with pytest.raises(InjectedTransferError):
            streaming.streamed_centered_gram(X_TALL, max_bytes=TILE_BYTES)
        assert supervisor.breaker.consecutive_failures == 3  # 1 + 2 retries

    def test_backoff_deterministic_and_exponential(self):
        d0 = supervisor.backoff_delay(0, tile_index=3, seed=1)
        d1 = supervisor.backoff_delay(1, tile_index=3, seed=1)
        d2 = supervisor.backoff_delay(2, tile_index=3, seed=1)
        assert d0 == supervisor.backoff_delay(0, tile_index=3, seed=1)
        base = 0.001  # SQ_RETRY_BACKOFF_S from the fixture
        for attempt, d in enumerate((d0, d1, d2)):
            assert base * 2 ** attempt <= d < 2 * base * 2 ** attempt
        assert supervisor.backoff_delay(0, tile_index=4, seed=1) != d0

    def test_injected_faults_recorded_as_jsonl(self, tmp_path):
        path = str(tmp_path / "faults.jsonl")
        rec = obs.enable(path)
        try:
            faults.arm("put_fail:tiles=1,times=1")
            streaming.streamed_centered_gram(X_TALL, max_bytes=TILE_BYTES)
            assert len(rec.fault_events) == 1
            for ev in rec.fault_events:
                assert validate_record(ev) == []
            assert rec.counters.get("resilience.retries", 0) == 1
        finally:
            obs.disable()


# -- circuit breaker ---------------------------------------------------------


class TestCircuitBreaker:
    def _fresh(self, monkeypatch, k=2, cooldown=10.0):
        monkeypatch.setenv("SQ_BREAKER_K", str(k))
        monkeypatch.setenv("SQ_BREAKER_COOLDOWN_S", str(cooldown))
        clock = {"t": 100.0}
        trips = []
        br = CircuitBreaker(clock=lambda: clock["t"],
                            trip_action=lambda: trips.append(True))
        return br, clock, trips

    def test_trips_after_k_consecutive_failures(self, monkeypatch):
        br, clock, trips = self._fresh(monkeypatch)
        br.record_failure("x")
        assert br.state() == CLOSED and not trips
        br.record_failure("x")
        assert br.state() == OPEN and trips == [True]
        assert br.trips == 1

    def test_success_resets_consecutive_count(self, monkeypatch):
        br, clock, trips = self._fresh(monkeypatch)
        br.record_failure("x")
        br.record_success()
        br.record_failure("x")
        assert br.state() == CLOSED and not trips

    def test_half_open_after_cooldown_then_probe_decides(self, monkeypatch):
        br, clock, trips = self._fresh(monkeypatch)
        br.record_failure("x")
        br.record_failure("x")
        assert br.state() == OPEN
        clock["t"] += 5.0
        assert br.state() == OPEN  # cooldown not elapsed
        clock["t"] += 6.0
        assert br.state() == HALF_OPEN
        br.on_probe("timeout")  # trial failed: re-open, cooldown restarts
        assert br.state() == OPEN
        clock["t"] += 11.0
        assert br.state() == HALF_OPEN
        br.on_probe("ok")
        assert br.state() == CLOSED
        states = [t["state"] for t in br.transitions]
        assert states == [OPEN, HALF_OPEN, OPEN, HALF_OPEN, CLOSED]

    def test_preflight_forces_fresh_probe(self, monkeypatch):
        monkeypatch.setenv("SQ_BREAKER_K", "1")
        monkeypatch.setenv("SQ_BREAKER_COOLDOWN_S", "0")
        br = supervisor.breaker
        br.trip_action = lambda: None
        calls = []

        def fake_probe(timeout_s=60, platform=None, force=False):
            calls.append(force)
            br.on_probe("ok")
            return {"outcome": "ok", "latency_s": 0.0, "platform": "x"}

        monkeypatch.setattr(probe_mod, "probe_device", fake_probe)
        br.record_failure("wedge")  # K=1: trips immediately
        assert br.preflight("test") == CLOSED
        assert calls == [True]  # the half-open trial bypassed the cache

    def test_probe_timeouts_trip_and_route_to_cpu(self, monkeypatch, tmp_path):
        """The acceptance wiring: injected probe timeouts feed the breaker
        through obs.probe, trip it at K, run the CPU escape, and emit
        schema-valid breaker JSONL."""
        monkeypatch.setenv("SQ_BREAKER_K", "2")
        escapes = []
        supervisor.breaker.trip_action = lambda: escapes.append(
            supervisor._cpu_escape())
        rec = obs.enable(str(tmp_path / "breaker.jsonl"))
        try:
            faults.arm("probe_timeout:n=2")
            probe_mod.probe_device(platform="fakeaccel", force=True)
            probe_mod.probe_device(platform="fakeaccel", force=True)
            assert supervisor.breaker.state() == OPEN
            assert escapes == [True]  # jax_platforms now pinned to cpu
            assert jax.default_backend() == "cpu"
            assert [e["state"] for e in rec.breaker_events] == [OPEN]
            for ev in rec.breaker_events:
                assert validate_record(ev) == []
            assert rec.gauges["resilience.breaker_state"] == OPEN
        finally:
            obs.disable()

    def test_deadline_exceeded_counts_as_timeout(self, monkeypatch):
        monkeypatch.setenv("SQ_TILE_DEADLINE_S", "0.005")
        monkeypatch.setenv("SQ_BREAKER_K", "2")
        trips = []
        supervisor.breaker.trip_action = lambda: trips.append(True)
        faults.arm("put_stall:p=1,s=0.02,times=1")
        # every tile stalls past the deadline once: consecutive timeouts
        # trip the breaker mid-pass, but the data still arrives — the
        # pass completes with the correct result
        mean_ref, Gc_ref, _ = streaming.streamed_centered_gram(
            X_TALL, max_bytes=TILE_BYTES)
        assert trips == [True]
        assert supervisor.breaker.state() == OPEN
        faults.disarm()
        mean_ok, Gc_ok, _ = streaming.streamed_centered_gram(
            X_TALL, max_bytes=TILE_BYTES)
        np.testing.assert_array_equal(np.asarray(Gc_ref), np.asarray(Gc_ok))


# -- probe TTL cache ---------------------------------------------------------


class TestProbeTTL:
    def test_cached_within_ttl_no_subprocess(self, monkeypatch):
        monkeypatch.setenv("SQ_PROBE_TTL_S", "300")
        monkeypatch.setenv("SQ_BREAKER_K", "99")
        faults.arm("probe_timeout:n=1")
        first = probe_mod.probe_device(platform="fakeaccel", timeout_s=1)
        assert first["outcome"] == "timeout" and "cached" not in first
        faults.disarm()

        def no_subprocess(*a, **kw):  # a cache hit must not spawn
            raise AssertionError("subprocess probe ran despite warm cache")

        monkeypatch.setattr(probe_mod.subprocess, "run", no_subprocess)
        second = probe_mod.probe_device(platform="fakeaccel", timeout_s=1)
        assert second["outcome"] == "timeout" and second["cached"] is True

    def test_cached_result_does_not_refeed_breaker(self, monkeypatch):
        monkeypatch.setenv("SQ_PROBE_TTL_S", "300")
        monkeypatch.setenv("SQ_BREAKER_K", "99")
        faults.arm("probe_timeout:n=1")
        probe_mod.probe_device(platform="fakeaccel", timeout_s=1)
        faults.disarm()
        before = supervisor.breaker.consecutive_failures
        assert before == 1  # the fresh timeout fed it once
        probe_mod.probe_device(platform="fakeaccel", timeout_s=1)
        assert supervisor.breaker.consecutive_failures == before

    def test_force_and_ttl_zero_bypass_cache(self, monkeypatch):
        monkeypatch.setenv("SQ_BREAKER_K", "99")
        faults.arm("probe_timeout:n=3")
        probe_mod.probe_device(platform="fakeaccel", timeout_s=1)
        forced = probe_mod.probe_device(platform="fakeaccel", timeout_s=1,
                                        force=True)
        assert "cached" not in forced  # injector consumed again
        monkeypatch.setenv("SQ_PROBE_TTL_S", "0")
        third = probe_mod.probe_device(platform="fakeaccel", timeout_s=1)
        assert "cached" not in third

    def test_cross_process_cache_file(self, monkeypatch, tmp_path):
        cache = str(tmp_path / "probe_cache.json")
        monkeypatch.setenv("SQ_PROBE_CACHE", cache)
        monkeypatch.setenv("SQ_PROBE_TTL_S", "300")
        monkeypatch.setenv("SQ_BREAKER_K", "99")
        import subprocess as sp

        def fake_run(*a, **kw):
            return sp.CompletedProcess(a, 0)

        monkeypatch.setattr(probe_mod.subprocess, "run", fake_run)
        first = probe_mod.probe_device(platform="fakeaccel", timeout_s=1)
        assert first["outcome"] == "ok"
        # a sibling process = fresh module state; the file serves the hit
        monkeypatch.setattr(probe_mod, "last_probe", None)
        monkeypatch.setattr(probe_mod, "_last_probe_t", None)
        monkeypatch.setattr(probe_mod.subprocess, "run", lambda *a, **kw: (
            _ for _ in ()).throw(AssertionError("file cache missed")))
        second = probe_mod.probe_device(platform="fakeaccel", timeout_s=1)
        assert second["outcome"] == "ok" and second["cached"] is True


# -- finiteness guard --------------------------------------------------------


class TestStrictFiniteness:
    def test_nan_tile_raises_with_provenance(self, monkeypatch):
        monkeypatch.setenv("SQ_RESILIENCE_STRICT", "1")
        faults.arm("nan:tiles=1")
        with pytest.raises(NonFiniteAccumulatorError, match="tile 1"):
            streaming.streamed_centered_gram(X_TALL, max_bytes=TILE_BYTES)

    def test_without_strict_nan_propagates_silently(self):
        faults.arm("nan:tiles=1")
        _, Gc, _ = streaming.streamed_centered_gram(X_TALL,
                                                    max_bytes=TILE_BYTES)
        assert not np.isfinite(np.asarray(Gc)).all()


# -- resumable streaming -----------------------------------------------------


class TestResume:
    def test_interrupt_then_resume_bitwise_parity(self, tmp_path):
        ckpt = streaming.StreamCheckpoint(str(tmp_path / "gram.npz"),
                                          every=2)
        mean_ref, Gc_ref, _ = streaming.streamed_centered_gram(
            X_TALL, max_bytes=TILE_BYTES)
        faults.arm("abort:tile=4,times=1")
        with pytest.raises(InjectedInterrupt):
            streaming.streamed_centered_gram(X_TALL, max_bytes=TILE_BYTES,
                                             checkpoint=ckpt)
        assert (tmp_path / "gram.npz").exists()

        puts = []
        real_put = jax.device_put

        def recording(x, *a, **kw):
            puts.append(int(getattr(x, "nbytes", 0)))
            return real_put(x, *a, **kw)

        jax.device_put, saved = recording, jax.device_put
        try:
            mean_r, Gc_r, _ = streaming.streamed_centered_gram(
                X_TALL, max_bytes=TILE_BYTES, checkpoint=ckpt)
        finally:
            jax.device_put = saved
        # the resumed pass re-uploads only the tiles past the cursor: the
        # abort fired while tile 4 staged (tile 3 still pending), so tiles
        # 0-2 folded and the every=2 snapshot left cursor 2 — the rerun
        # puts tiles 2..6 (5 of 7), never the full walk
        tile_puts = [s for s in puts if s >= 64 * ROW_BYTES]
        assert len(tile_puts) == 5
        np.testing.assert_array_equal(np.asarray(Gc_r), np.asarray(Gc_ref))
        np.testing.assert_array_equal(np.asarray(mean_r),
                                      np.asarray(mean_ref))
        assert not (tmp_path / "gram.npz").exists()  # completed: removed

    def test_mismatched_checkpoint_is_ignored(self, tmp_path):
        ckpt = streaming.StreamCheckpoint(str(tmp_path / "gram.npz"),
                                          every=2)
        faults.arm("abort:tile=4,times=1")
        with pytest.raises(InjectedInterrupt):
            streaming.streamed_centered_gram(X_TALL, max_bytes=TILE_BYTES,
                                             checkpoint=ckpt)
        faults.disarm()
        other = X_TALL + 1.0  # different data, same shape/dtype/tile plan
        mean_ref, Gc_ref, _ = streaming.streamed_centered_gram(
            other, max_bytes=TILE_BYTES)
        mean_o, Gc_o, _ = streaming.streamed_centered_gram(
            other, max_bytes=TILE_BYTES, checkpoint=ckpt)
        np.testing.assert_array_equal(np.asarray(Gc_o), np.asarray(Gc_ref))

    def test_interior_data_change_invalidates_checkpoint(self, tmp_path):
        """Re-shuffled/re-cleaned interior rows with identical first and
        last rows must NOT resume a stale accumulator — the strided-sample
        digest catches what the old first/last-row digest let through."""
        ckpt = streaming.StreamCheckpoint(str(tmp_path / "gram.npz"),
                                          every=2)
        faults.arm("abort:tile=4,times=1")
        with pytest.raises(InjectedInterrupt):
            streaming.streamed_centered_gram(X_TALL, max_bytes=TILE_BYTES,
                                             checkpoint=ckpt)
        faults.disarm()
        other = X_TALL.copy()
        other[1:-1] = X_TALL[-2:0:-1]  # reverse the interior rows only
        np.testing.assert_array_equal(other[0], X_TALL[0])
        np.testing.assert_array_equal(other[-1], X_TALL[-1])
        assert streaming._data_digest(other) != streaming._data_digest(
            X_TALL)
        mean_ref, Gc_ref, _ = streaming.streamed_centered_gram(
            other, max_bytes=TILE_BYTES)
        mean_o, Gc_o, _ = streaming.streamed_centered_gram(
            other, max_bytes=TILE_BYTES, checkpoint=ckpt)
        np.testing.assert_array_equal(np.asarray(Gc_o), np.asarray(Gc_ref))
        np.testing.assert_array_equal(np.asarray(mean_o),
                                      np.asarray(mean_ref))

    def test_prestats_ingest_opts_out_of_env_checkpointing(
            self, monkeypatch, tmp_path):
        """streamed_prestats' accumulator is the dataset-sized resident
        buffer: with SQ_STREAM_CKPT_DIR armed it must write NO checkpoint
        (each snapshot would be an O(n·m) host sync + npz)."""
        from sq_learn_tpu.utils import checkpoint as ckpt_mod

        monkeypatch.setenv("SQ_STREAM_CKPT_DIR", str(tmp_path))
        monkeypatch.setenv("SQ_STREAM_CKPT_EVERY", "1")

        def no_snapshot(*a, **kw):
            raise AssertionError("ingest fold wrote a checkpoint")

        monkeypatch.setattr(ckpt_mod, "save_stream_state", no_snapshot)
        out = streaming.streamed_prestats(X_TALL, max_bytes=TILE_BYTES)
        assert not list(tmp_path.iterdir())
        np.testing.assert_allclose(np.asarray(out["mean"]),
                                   X_TALL.mean(axis=0), rtol=1e-5,
                                   atol=1e-5)

    def test_resumed_qpca_fit_matches_uninterrupted_exactly(
            self, monkeypatch, tmp_path):
        """The acceptance scenario end-to-end at estimator level: a
        streamed qPCA fit interrupted mid-Gram-pass, rerun with the
        env-armed checkpoint dir, resumes and publishes fitted state
        identical to the never-interrupted fit."""
        from sq_learn_tpu.models import QPCA

        monkeypatch.setenv("SQ_STREAM_TILE_BYTES", str(TILE_BYTES))
        monkeypatch.setenv("SQ_STREAM_CKPT_DIR", str(tmp_path))
        monkeypatch.setenv("SQ_STREAM_CKPT_EVERY", "2")

        def fit():
            return QPCA(n_components=3, svd_solver="full", random_state=0,
                        ingest="streamed").fit(X_TALL)

        ref = fit()
        faults.arm("abort:tile=4,times=1")
        with pytest.raises(InjectedInterrupt):
            fit()
        assert any(f.suffix == ".npz" for f in tmp_path.iterdir())
        resumed = fit()
        for attr in ("mean_", "components_", "singular_values_",
                     "explained_variance_", "left_sv"):
            np.testing.assert_array_equal(
                np.asarray(getattr(resumed, attr)),
                np.asarray(getattr(ref, attr)), err_msg=attr)
        assert not any(f.suffix == ".npz" for f in tmp_path.iterdir())

    def test_sharded_gram_resume_parity(self, mesh8, tmp_path):
        from sq_learn_tpu.parallel.streaming import \
            streamed_centered_gram_sharded

        ckpt = streaming.StreamCheckpoint(str(tmp_path / "gram.npz"),
                                          every=2)
        mean_ref, Gc_ref, _ = streamed_centered_gram_sharded(
            mesh8, X_TALL, max_bytes=TILE_BYTES)
        faults.arm("abort:tile=4,times=1")
        with pytest.raises(InjectedInterrupt):
            streamed_centered_gram_sharded(mesh8, X_TALL,
                                           max_bytes=TILE_BYTES,
                                           checkpoint=ckpt)
        mean_r, Gc_r, _ = streamed_centered_gram_sharded(
            mesh8, X_TALL, max_bytes=TILE_BYTES, checkpoint=ckpt)
        np.testing.assert_array_equal(np.asarray(Gc_r), np.asarray(Gc_ref))
        np.testing.assert_array_equal(np.asarray(mean_r),
                                      np.asarray(mean_ref))


# -- supervised whole-array placement ---------------------------------------


class TestResidentPutSupervised:
    def test_transient_failure_recovers(self):
        from sq_learn_tpu.streaming import streamed_resident_put

        plan = faults.arm("put_fail:tiles=1,times=1")
        out = streamed_resident_put(X_TALL, max_bytes=TILE_BYTES)
        assert [ev["kind"] for ev in plan.events] == ["put_fail"]
        np.testing.assert_array_equal(np.asarray(out), X_TALL)


# -- schema ------------------------------------------------------------------


class TestSchema:
    def test_fault_and_breaker_records_validate(self):
        base = {"v": 1, "ts": 1.0}
        assert validate_record(dict(base, type="fault", kind="put_fail",
                                    tile=3)) == []
        assert validate_record(dict(base, type="fault", kind="probe_timeout",
                                    tile=None)) == []
        assert validate_record(dict(base, type="breaker", state="open",
                                    prev="closed", reason="r",
                                    consecutive=3)) == []

    @pytest.mark.parametrize("rec", [
        {"type": "fault", "kind": 7, "tile": 1},
        {"type": "fault", "kind": "x", "tile": "one"},
        {"type": "breaker", "state": "melted", "prev": "closed",
         "reason": "r", "consecutive": 1},
        {"type": "breaker", "state": "open", "prev": "closed",
         "reason": "r", "consecutive": -1},
    ])
    def test_invalid_records_rejected(self, rec):
        assert validate_record(dict(rec, v=1, ts=1.0)) != []
