"""Property tests for the quantum estimation routines (SURVEY §4 test plan:
error bounds hold with the advertised probability, vectorized over many seeds
so they're cheap on accelerators)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sq_learn_tpu.ops.quantum import (
    amplitude_estimation,
    amplitude_estimation_M,
    amplitude_estimation_per_eps,
    consistent_phase_estimation,
    inner_product_estimates,
    ipe,
    median_evaluation,
    median_q,
    phase_estimation,
    phase_estimation_m,
)
from sq_learn_tpu.ops.quantum.sampling import fejer_grid_sample, fejer_probs


def exact_fejer_pmf(pos, M):
    """Reference pmf: p(j) ∝ |sin(π(pos−j))/(M sin(π(pos−j)/M))|², j=0..M−1,
    circular — mirrors Utility.py:498-506 built in numpy."""
    j = np.arange(M)
    diff = (pos - j) / M
    diff = diff - np.round(diff)  # circular distance in grid-fraction units
    p = np.empty(M)
    for i, d in enumerate(diff):
        if abs(np.sin(np.pi * d)) < 1e-15:
            p[i] = 1.0
        else:
            p[i] = (np.sin(np.pi * M * d) / (M * np.sin(np.pi * d))) ** 2
    return p / p.sum()


class TestFejerSampler:
    def test_matches_exact_pmf_small_M(self, key):
        M = 32
        pos = 7.3
        n = 40000
        j = fejer_grid_sample(key, jnp.full((n,), pos), float(M), window=64)
        counts = np.bincount(np.asarray(j).astype(int), minlength=M)
        emp = counts / n
        pmf = exact_fejer_pmf(pos, M)
        assert 0.5 * np.abs(emp - pmf).sum() < 0.02  # total variation

    def test_wraps_circularly(self, key):
        # true value at grid position 0.2 → mass on both j=0 and j=M−1 side
        M = 64
        j = np.asarray(fejer_grid_sample(key, jnp.full((20000,), 0.2), float(M), 32))
        assert j.min() >= 0 and j.max() <= M - 1
        assert (j > M / 2).mean() > 0.02  # wrapped mass present

    def test_per_element_traced_M(self, key):
        Ms = jnp.array([8.0, 64.0, 1024.0])
        pos = jnp.array([2.2, 31.7, 512.4])
        j = fejer_grid_sample(key, pos, Ms, window=16)
        assert j.shape == (3,)
        assert (np.asarray(j) < np.asarray(Ms)).all()

    def test_probs_limit(self):
        assert float(fejer_probs(0.0, 32)) == 1.0
        assert float(fejer_probs(1.0, 32)) == 1.0  # integer distance → peak


class TestAmplitudeEstimation:
    def test_error_bound(self, key):
        a = jax.random.uniform(jax.random.PRNGKey(7), (500,), minval=0.02, maxval=0.98)
        eps = 0.01
        est = amplitude_estimation(key, a, epsilon=eps, gamma=0.05)
        # standard AE bound: |ã−a| ≤ 2πε√(a(1−a)) + π²ε² w.p. ≥ 1−γ
        bound = 2 * np.pi * eps * np.sqrt(np.asarray(a * (1 - a))) + (np.pi * eps) ** 2
        frac_ok = (np.abs(np.asarray(est - a)) <= bound).mean()
        assert frac_ok >= 0.93

    def test_exact_endpoints(self, key):
        est = amplitude_estimation(key, jnp.array([0.0, 1.0]), epsilon=0.01, gamma=0.01)
        np.testing.assert_allclose(np.asarray(est), [0.0, 1.0], atol=5e-3)

    def test_M_formula(self):
        # reference Utility.py:484
        assert amplitude_estimation_M(0.01) == int(
            np.ceil((np.pi / 0.02) * (1 + np.sqrt(1.04)))
        )

    def test_scalar_shape(self, key):
        est = amplitude_estimation(key, 0.3, epsilon=0.05)
        assert est.shape == ()

    def test_per_eps_variant(self, key):
        a = jnp.full((200,), 0.4)
        eps = jnp.geomspace(0.001, 0.1, 200)
        est = amplitude_estimation_per_eps(key, a, eps, Q=13)
        err = np.abs(np.asarray(est) - 0.4)
        # finer epsilon → smaller error on average
        assert err[:50].mean() < err[-50:].mean() + 0.02
        assert (err <= 4 * np.asarray(eps) + 1e-3).mean() > 0.9


class TestPhaseEstimation:
    def test_matches_pmf(self, key):
        m, omega = 6, 0.37
        M = 2**m
        est = phase_estimation(key, jnp.full((30000,), omega), m=m)
        ks = np.asarray(est * M).astype(int)
        emp = np.bincount(ks, minlength=M) / len(ks)
        pmf = exact_fejer_pmf(omega * M, M)
        assert 0.5 * np.abs(emp - pmf).sum() < 0.02

    def test_error_bound(self, key):
        eps, gamma = 0.01, 0.1
        omega = jax.random.uniform(jax.random.PRNGKey(3), (500,))
        est = phase_estimation(key, omega, epsilon=eps, gamma=gamma)
        err = np.abs(np.asarray(est - omega))
        err = np.minimum(err, 1 - err)  # circular
        assert (err <= eps).mean() >= 1 - gamma - 0.03

    def test_omega_one_special_case(self, key):
        m = 5
        est = phase_estimation(key, jnp.array([1.0]), m=m)
        assert float(est[0]) == (2**m - 1) / 2**m

    def test_m_formula(self):
        # Nielsen & Chuang eq. 5.35, reference Utility.py:635
        assert phase_estimation_m(0.01, 0.1) == int(
            np.ceil(np.log2(100)) + np.ceil(np.log2(2 + 1 / 0.2))
        )


class TestConsistentPhaseEstimation:
    def test_consistency(self, key):
        # the whole point: repeated calls agree almost always (Utility.py:770)
        omega = 0.4321
        keys = jax.random.split(key, 50)
        ests = np.array([
            float(consistent_phase_estimation(k, omega, epsilon=0.05, gamma=0.1))
            for k in keys
        ])
        values, counts = np.unique(np.round(ests, 6), return_counts=True)
        assert counts.max() / len(ests) >= 0.9

    def test_accuracy(self, key):
        omega = jax.random.uniform(jax.random.PRNGKey(11), (200,),
                                   minval=0.05, maxval=0.95)
        est = consistent_phase_estimation(key, omega, epsilon=0.02, gamma=0.1)
        assert (np.abs(np.asarray(est - omega)) <= 2 * 0.02).mean() > 0.95

    def test_non_negative(self, key):
        est = consistent_phase_estimation(key, jnp.array([0.001]),
                                          epsilon=0.05, gamma=0.1)
        assert float(est[0]) >= 0.0


class TestMedianEvaluation:
    def test_q_odd_and_formula(self):
        for gamma in (0.3, 0.1, 0.01, 0.001):
            q = median_q(gamma)
            assert q % 2 == 1
            z = np.log(1 / gamma) / (2 * (8 / np.pi**2 - 0.5) ** 2)
            assert q in (int(np.ceil(z)), int(np.ceil(z)) + 1)

    def test_boosts_concentration(self, key):
        noisy = lambda key: jax.random.normal(key) * 0.5 + 1.0
        est = median_evaluation(noisy, key, gamma=0.001)
        assert abs(float(est) - 1.0) < 0.5


class TestIPE:
    def test_relative_error_guarantee(self, key):
        # RIPE: |s − ⟨x,y⟩| ≤ ε·max(1, |⟨x,y⟩|) w.p. ≥ 1−γ
        kx, ky = jax.random.split(jax.random.PRNGKey(5))
        x = jax.random.normal(kx, (300, 20))
        y = jax.random.normal(ky, (300, 20))
        true_ip = jnp.sum(x * y, axis=1)
        eps = 0.05
        s = ipe(
            key,
            jnp.sum(x * x, axis=1),
            jnp.sum(y * y, axis=1),
            true_ip,
            epsilon=eps,
            gamma=0.05,
        )
        tol = eps * np.maximum(1.0, np.abs(np.asarray(true_ip)))
        assert (np.abs(np.asarray(s - true_ip)) <= tol).mean() >= 0.9

    @pytest.mark.slow
    def test_matrix_pairs(self, key):
        X = jax.random.normal(jax.random.PRNGKey(1), (40, 8))
        C = jax.random.normal(jax.random.PRNGKey(2), (5, 8))
        est = inner_product_estimates(key, X, C, epsilon=0.01, gamma=0.1)
        assert est.shape == (40, 5)
        true = np.asarray(X @ C.T)
        tol = 0.05 * np.maximum(1.0, np.abs(true))
        assert (np.abs(np.asarray(est) - true) <= tol).mean() > 0.9

    def test_jittable(self, key):
        f = jax.jit(
            lambda k, x2, y2, ip: ipe(k, x2, y2, ip, epsilon=0.1, Q=5)
        )
        out = f(key, jnp.array(2.0), jnp.array(3.0), jnp.array(1.5))
        assert np.isfinite(float(out))


class TestFejerTail:
    """Pin the windowed Fejér sampler's truncation effect at M ≫ 2·window+1
    (VERDICT round 1 weak #7): the tail mass is O(1/window) small, and the
    AE within-ε-w.p.-≥1−γ guarantee survives truncation (which renormalizes
    mass toward the true value — conservative by construction)."""

    @pytest.mark.parametrize("M", [400, 3163, 31429])
    def test_truncated_mass_is_small(self, M):
        """Exact truncated mass (computed from the full pmf) ≤ 1% at
        window=64, for grids far beyond the window."""
        window = 64
        pos = 0.37 * M  # generic off-grid position
        j = np.arange(M)
        # circular grid distance
        delta = (pos - j) / M
        delta = delta - np.round(delta)
        p = np.asarray(fejer_probs(jnp.asarray(delta), float(M)))
        p = p / p.sum()
        inside = np.abs(pos - j - np.round((pos - j) / M) * M) <= window
        truncated = p[~inside].sum()
        assert truncated < 0.01
        # and the head the sampler keeps concentrates ≥ 99% of the mass
        assert p[inside].sum() > 0.99

    def test_ae_guarantee_small_epsilon(self, key):
        """ε=0.001 → M ≈ 3143 ≫ 129 enumerated points: amplitude estimates
        must still land within ε of the truth w.p. ≥ 1−γ (γ=0.05)."""
        eps, gamma = 1e-3, 0.05
        trials = 4000
        for a0 in (0.11, 0.5, 0.83):
            a = jnp.full((trials,), a0)
            est = amplitude_estimation(key, a, epsilon=eps, gamma=gamma)
            ok = (np.abs(np.asarray(est) - a0) <= eps).mean()
            assert ok >= 1 - gamma, (a0, ok)

    def test_single_shot_success_floor(self, key):
        """Without median boosting the single-trial success probability must
        clear the 8/π² AE floor — truncation may only help, never hurt."""
        eps = 1e-3
        trials = 6000
        a = jnp.full((trials,), 0.27)
        est = amplitude_estimation(key, a, epsilon=eps)
        ok = (np.abs(np.asarray(est) - 0.27) <= eps).mean()
        assert ok >= 8 / np.pi**2 - 0.02  # binomial noise margin

    @pytest.mark.slow
    def test_exact_when_window_covers_grid(self, key):
        """M ≤ 2·window+1: the sampler enumerates every residue — empirical
        frequencies must match the exact pmf (TV ≤ sampling noise)."""
        M, window, n = 101, 64, 200_000
        pos = 0.43 * M
        draws = np.asarray(fejer_grid_sample(
            key, jnp.full((n,), pos), float(M), window))
        emp = np.bincount(draws.astype(int), minlength=M) / n
        j = np.arange(M)
        delta = (pos - j) / M
        delta = delta - np.round(delta)
        p = np.asarray(fejer_probs(jnp.asarray(delta), float(M)))
        p = p / p.sum()
        tv = 0.5 * np.abs(emp - p).sum()
        assert tv < 0.02


class TestPhaseArgumentWrappers:
    """sv_to_theta / theta_to_sv (reference wrapper/unwrap_phase_est_arguments,
    ``Utility.py:575-587``) — exact inverses and range behavior."""

    def test_round_trip(self):
        from sq_learn_tpu.ops.quantum.estimation import sv_to_theta, theta_to_sv

        sv = jnp.linspace(0.0, 1.0, 11)
        for eps in (0.1, 0.01):
            theta = sv_to_theta(sv, eps)
            back = theta_to_sv(theta, eps)
            np.testing.assert_allclose(np.asarray(back), np.asarray(sv),
                                       rtol=1e-5, atol=1e-6)
            assert np.all(np.asarray(theta) >= 0)

    def test_out_of_range_clipped(self):
        from sq_learn_tpu.ops.quantum.estimation import sv_to_theta

        theta = sv_to_theta(jnp.asarray([-2.0, 2.0]), 0.1)
        assert np.all(np.isfinite(np.asarray(theta)))


class TestIPEWindowEquivalence:
    """The q-means IPE E-step runs the Fejér sampler at window=16 (see
    e_step); this pins that the narrowed window does not change the
    estimate error distribution relative to the sampler default — the
    rescaled per-pair precisions put most grid sizes far beyond any
    practical window, so truncation dominates at every width and only
    ever tightens the within-ε guarantee."""

    @pytest.mark.slow
    def test_estimates_match_across_windows(self):
        import jax
        import jax.numpy as jnp

        from sq_learn_tpu.ops.quantum.estimation import ipe_matrix

        rng = np.random.default_rng(2)
        X = rng.normal(0, 1, (400, 16)).astype(np.float32)
        C = rng.normal(0, 1, (8, 16)).astype(np.float32)
        x2 = (X**2).sum(axis=1)
        c2 = (C**2).sum(axis=1)
        inner = X @ C.T
        errs = {}
        for w in (16, 64):
            est = np.asarray(ipe_matrix(
                jax.random.PRNGKey(0), jnp.asarray(inner), jnp.asarray(x2),
                jnp.asarray(c2), epsilon=0.25, Q=5, window=w))
            errs[w] = np.abs(est - inner)
        # same error scale at both widths (medians within 20%)
        m16, m64 = np.median(errs[16]), np.median(errs[64])
        assert 0.8 * m64 <= m16 <= 1.2 * m64
        # and the narrow window is never grossly worse in the tail
        assert np.percentile(errs[16], 99) <= 1.5 * np.percentile(
            errs[64], 99)
