"""QLSSVC tests: LS-SVM solve correctness, kernel dispatch, quantum error
model, complexity accounting (the reference ships zero tests — SURVEY §4)."""

import numpy as np
import pytest
import sklearn.datasets

from sq_learn_tpu import clone
from sq_learn_tpu.models import QLSSVC
from sq_learn_tpu.models.qlssvc import lssvc_solve, relative_error_routine


@pytest.fixture(scope="module")
def binary_data():
    X, y = sklearn.datasets.make_classification(
        n_samples=120, n_features=10, n_informative=6, random_state=5,
        class_sep=2.0)
    y = np.where(y == 0, -1.0, 1.0)
    return X.astype(np.float64), y


class TestSolve:
    def test_saddle_system_solution(self, binary_data):
        """b, α must satisfy the KKT system exactly (full-rank solve)."""
        X, y = binary_data
        import jax.numpy as jnp

        K = np.asarray(X @ X.T)
        penalty = 0.5
        b, alpha, s, cond, normF = lssvc_solve(
            jnp.asarray(K), y, penalty)
        alpha = np.asarray(alpha)
        # KKT: Σα = 0 and K·α + α/γ + b = y
        assert abs(np.sum(alpha)) < 1e-2
        resid = K @ alpha + alpha / penalty + float(b) - y
        assert np.max(np.abs(resid)) < 1e-2
        assert cond >= 1.0
        assert normF == pytest.approx(np.max(s))

    def test_low_rank_truncation(self, binary_data):
        X, y = binary_data
        import jax.numpy as jnp

        K = jnp.asarray(X @ X.T)
        _, _, s_full, _, _ = lssvc_solve(K, y, 0.5)
        _, _, s_trunc, _, _ = lssvc_solve(K, y, 0.5, var=0.9)
        assert len(s_trunc) < len(s_full)
        np.testing.assert_allclose(s_trunc, s_full[: len(s_trunc)],
                                   rtol=1e-4)

    def test_int_var_truncation(self, binary_data):
        X, y = binary_data
        import jax.numpy as jnp

        K = jnp.asarray(X @ X.T)
        _, _, s, cond, _ = lssvc_solve(K, y, 0.5, var=10)
        assert len(s) == 10
        assert cond == pytest.approx(float(s[0] / s[9]))


class TestClassification:
    @pytest.mark.parametrize("kernel", ["linear", "poly", "rbf", "sigmoid"])
    def test_kernels_classical_accuracy(self, binary_data, kernel):
        X, y = binary_data
        clf = QLSSVC(kernel=kernel, penalty=1.0, random_state=0).fit(X, y)
        acc = np.mean(clf.classical_predict(X) == y)
        assert acc > (0.9 if kernel != "sigmoid" else 0.6)

    def test_quantum_predict_small_error_matches_classical(self, binary_data):
        X, y = binary_data
        clf = QLSSVC(kernel="rbf", penalty=1.0, absolute_error=1e-6,
                     random_state=0).fit(X, y)
        agree = np.mean(clf.predict(X) == clf.classical_predict(X))
        assert agree > 0.98

    def test_relative_error_mode_runs(self, binary_data):
        X, y = binary_data
        clf = QLSSVC(kernel="linear", penalty=1.0, error_type="relative",
                     relative_error=0.1, random_state=0).fit(X, y)
        preds = clf.predict(X[:20])
        assert set(np.unique(preds)) <= {-1.0, 1.0}

    def test_score_accuracy(self, binary_data):
        X, y = binary_data
        clf = QLSSVC(kernel="rbf", penalty=1.0, absolute_error=1e-4,
                     random_state=0).fit(X, y)
        assert clf.score(X, y) > 0.9

    def test_linear_primal_coef(self, binary_data):
        X, y = binary_data
        clf = QLSSVC(kernel="linear", penalty=1.0, random_state=0).fit(X, y)
        # primal w reproduces the decision values: h = w·x + b
        h_primal = X @ clf.coef_ + clf.b_
        np.testing.assert_allclose(h_primal, clf.get_h(X), rtol=1e-3,
                                   atol=1e-3)

    def test_invalid_error_type(self):
        with pytest.raises(ValueError, match="absolute.*relative"):
            QLSSVC(error_type="bogus")

    def test_clone(self):
        est = QLSSVC(kernel="rbf", penalty=2.0, low_rank=True, var=0.8)
        assert clone(est).get_params() == est.get_params()


class TestQuantumErrorModel:
    def test_get_P_in_unit_interval(self, binary_data):
        X, y = binary_data
        clf = QLSSVC(kernel="rbf", penalty=1.0, random_state=0).fit(X, y)
        P = clf.get_P(X)
        assert np.all((P >= 0) & (P <= 1))
        # P ≤ 0.5 ⟺ h ≥ 0 ⟺ class +1
        np.testing.assert_array_equal(P <= 0.5, clf.get_h(X) >= 0)

    def test_betas_positive_and_formula(self, binary_data):
        X, y = binary_data
        clf = QLSSVC(kernel="linear", penalty=1.0, random_state=0).fit(X, y)
        betas = clf.get_betas(X)
        N = len(X)
        expected = np.sqrt(
            (N * np.sum(X**2, axis=1) + 1) * clf.Nu_)
        np.testing.assert_allclose(betas, expected, rtol=1e-4)

    def test_relative_error_routine_bounds(self, key):
        x_max = np.array([8.0, 4.0, 16.0])
        x_real = np.array([1.0, 0.5, 2.0])
        x_hat, delta_r, eps = relative_error_routine(
            key, x_max, x_real, relative_error=0.2)
        x_hat = np.asarray(x_hat)
        # the halving search stops once the noisy estimate ≥ current scale;
        # the final absolute ε is proportional to the final scale
        assert np.all(np.asarray(eps) > 0)
        assert np.all(np.abs(x_hat - x_real) <= np.asarray(eps) + 1e-6)

    def test_approx_hyperplane_close(self, binary_data):
        X, y = binary_data
        # absolute mode must honor absolute_error (the reference reads
        # relative_error in this branch, _qSVM.py:317) — a huge
        # relative_error must have no effect here
        clf = QLSSVC(kernel="linear", penalty=1.0, absolute_error=0.01,
                     relative_error=1e6, random_state=0).fit(X, y)
        b_approx, coef_approx = clf.get_approximated_hyperplane(X[:1])
        rel = np.linalg.norm(coef_approx - clf.coef_) / np.linalg.norm(
            clf.coef_)
        assert rel < 0.1

    def test_complexities_positive(self, binary_data):
        X, y = binary_data
        clf = QLSSVC(kernel="rbf", penalty=1.0, random_state=0).fit(X, y)
        assert clf.get_training_complexity() > 0
        assert np.all(clf.get_classification_complexity(X[:5]) > 0)
        assert np.all(
            clf.get_classification_complexity(X[:5], relative_error=True) > 0)
        betas, hs, Ps, cond, rel_c, abs_c = clf.get_all_attributes(X[:5])
        assert len(betas) == len(hs) == len(Ps) == 5
