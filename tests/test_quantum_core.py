"""Tests for QuantumState, noise injectors, tomography and μ-norm search."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sq_learn_tpu.ops.quantum import (
    QuantumState,
    best_mu,
    coupon_collect,
    estimate_wald,
    gaussian_estimate,
    introduce_error,
    introduce_error_array,
    linear_search,
    mu,
    multinomial_counts,
    real_tomography,
    tomography,
    tomography_incremental,
    tomography_n_measurements,
)


def random_unit(seed, d):
    v = np.random.RandomState(seed).randn(d)
    return v / np.linalg.norm(v)


class TestQuantumState:
    def test_normalizes(self):
        qs = QuantumState(jnp.arange(4), jnp.array([1.0, 2.0, 3.0, 4.0]))
        np.testing.assert_allclose(float(jnp.sum(qs.probabilities)), 1.0, atol=1e-6)

    def test_measure_counts(self, key):
        amps = jnp.array([3.0, 4.0])  # probs 9/25, 16/25
        qs = QuantumState(jnp.array([0, 1]), amps)
        counts = qs.measure_counts(key, 100000)
        freq = np.asarray(counts) / 100000
        np.testing.assert_allclose(freq, [0.36, 0.64], atol=0.01)

    def test_measure_values(self, key):
        qs = QuantumState(jnp.array([10.0, 20.0]), jnp.array([1.0, 1.0]))
        vals = np.asarray(qs.measure(key, 100))
        assert set(np.unique(vals)) <= {10.0, 20.0}

    def test_get_state(self):
        qs = QuantumState(jnp.array([5, 6]), jnp.array([1.0, 1.0]))
        state = qs.get_state()
        np.testing.assert_allclose(list(state.values()), [0.5, 0.5], atol=1e-6)

    def test_length_mismatch_raises(self):
        with pytest.raises(ValueError):
            QuantumState(jnp.arange(3), jnp.array([1.0, 1.0]))

    def test_wald(self, key):
        counts = multinomial_counts(key, 1000, jnp.array([0.5, 0.5]))
        est = estimate_wald(counts, 1000)
        np.testing.assert_allclose(float(est.sum()), 1.0, atol=1e-6)

    def test_coupon_collect(self, key):
        qs = QuantumState(jnp.arange(5), jnp.ones(5))
        n = int(coupon_collect(key, qs))
        assert n >= 5  # needs at least d draws


class TestNoise:
    def test_introduce_error_bounded(self, key):
        vals = jnp.zeros(1000)
        out = introduce_error(key, vals, 0.1)
        assert np.abs(np.asarray(out)).max() <= 0.1 + 1e-6

    def test_introduce_error_array_l2(self, key):
        arr = jnp.zeros(100)
        out = introduce_error_array(key, arr, 0.5)
        assert float(jnp.linalg.norm(out)) <= 0.5 + 1e-5

    def test_gaussian_estimate_l2_bound(self, key):
        v = jnp.asarray(random_unit(0, 64))
        est = gaussian_estimate(key, v, 0.3)
        assert float(jnp.linalg.norm(est - v)) <= 0.3 + 1e-5

    def test_zero_noise_identity(self, key):
        # reference bug: make_gaussian_est returns undefined var at noise==0
        v = jnp.asarray(random_unit(1, 16))
        np.testing.assert_array_equal(
            np.asarray(gaussian_estimate(key, v, 0.0)), np.asarray(v))


class TestTomography:
    def test_n_formula(self):
        d, delta = 784, 0.1
        assert (tomography_n_measurements(d, delta, "L2")
                == int(36 * d * np.log(d) / delta**2))
        assert (tomography_n_measurements(d, delta, "inf")
                == int(36 * np.log(d) / delta**2))

    def test_l2_error_bound(self, key):
        d, delta = 50, 0.3
        v = jnp.asarray(random_unit(2, d))
        est = real_tomography(key, v, delta=delta)
        assert float(jnp.linalg.norm(est - v)) <= delta

    def test_sign_resolution(self, key):
        # components with non-negligible mass must come back with right sign
        v = jnp.asarray(random_unit(3, 20))
        est = np.asarray(real_tomography(key, v, delta=0.1))
        big = np.abs(np.asarray(v)) > 0.15
        assert (np.sign(est[big]) == np.sign(np.asarray(v)[big])).all()

    def test_preserves_norm_by_default(self, key):
        v = 5.0 * jnp.asarray(random_unit(4, 30))
        est = real_tomography(key, v, delta=0.2)
        np.testing.assert_allclose(float(jnp.linalg.norm(est)), 5.0, rtol=0.05)
        raw = real_tomography(key, v, delta=0.2, preserve_norm=False)
        np.testing.assert_allclose(float(jnp.linalg.norm(raw)), 1.0, rtol=0.05)

    def test_matrix_vmap(self, key):
        A = jnp.asarray(np.vstack([random_unit(s, 16) for s in range(4)]))
        est = tomography(key, A, 0.3)
        assert est.shape == A.shape
        errs = np.linalg.norm(np.asarray(est - A), axis=1)
        assert (errs <= 0.3).all()

    def test_zero_noise_identity(self, key):
        A = jnp.asarray(np.random.RandomState(0).randn(3, 5))
        out = tomography(key, A, 0.0)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(A))

    def test_gaussian_path_matrix(self, key):
        A = jnp.asarray(np.random.RandomState(1).randn(4, 6))
        out = tomography(key, A, 0.2, true_tomography=False)
        # flat-reshape semantics: total perturbation ≤ noise in Frobenius
        assert float(jnp.linalg.norm(out - A)) <= 0.2 + 1e-5

    def test_incremental_early_stop(self, key):
        v = jnp.asarray(random_unit(5, 12))
        res = tomography_incremental(key, v, delta=0.4)
        ns = list(res.keys())
        assert ns == sorted(ns)
        final = res[ns[-1]]
        assert np.linalg.norm(final - np.asarray(v)) <= 0.4 * 1.5


class TestMuNorms:
    @staticmethod
    def numpy_mu(p, A):
        # straight transcription of the μ_p definition (Utility.py:196-212)
        def s(q, M):
            if q == 0:
                return max(np.count_nonzero(M[i]) for i in range(len(M)))
            return np.max(np.sum(np.abs(M) ** q, axis=1))

        return np.sqrt(s(2 * p, A) * s(2 * (1 - p), A.T))

    def test_matches_definition(self):
        A = np.random.RandomState(0).randn(10, 6)
        for p in (0.0, 0.3, 0.5, 1.0):
            np.testing.assert_allclose(float(mu(A, p)), self.numpy_mu(p, A), rtol=1e-5)

    def test_linear_search_minimizes(self):
        A = np.random.RandomState(1).randn(12, 8)
        best_p, best_val = linear_search(A, 0.0, 1.0, 0.1)
        grid = list(np.arange(0.0, 1.0, 0.1)) + [1.0]
        vals = [self.numpy_mu(p, A) for p in grid]
        np.testing.assert_allclose(best_val, min(vals), rtol=1e-5)

    def test_best_mu_vs_frobenius(self):
        A = np.eye(8)
        desc, val = best_mu(A)
        assert val <= np.linalg.norm(A) + 1e-6
        assert desc.startswith("p=") or desc == "Frobenius"


class TestMagnitudeTomographySigned:
    """Legacy fake-sign tomography (reference L2_tomogrphy_fakeSign,
    Utility.py:234-256)."""

    def test_estimates_with_true_signs(self):
        from sq_learn_tpu.ops.quantum import magnitude_tomography_signed

        rng = np.random.default_rng(0)
        v = rng.normal(size=32).astype(np.float32)
        v /= np.linalg.norm(v)
        est = np.asarray(magnitude_tomography_signed(
            jax.random.PRNGKey(0), v, delta=0.1))
        assert np.linalg.norm(est - v) <= 0.1  # L2 guarantee, w.h.p.
        nz = np.abs(v) > 1e-3
        assert np.all(np.sign(est[nz]) == np.sign(v[nz]))  # true signs

    def test_reference_alias(self):
        import sq_learn_tpu.QuantumUtility as QU

        assert QU.L2_tomogrphy_fakeSign is QU.magnitude_tomography_signed

    def test_zero_delta_exact(self):
        from sq_learn_tpu.ops.quantum import magnitude_tomography_signed

        v = np.array([0.6, -0.8], np.float32)
        out = np.asarray(magnitude_tomography_signed(
            jax.random.PRNGKey(0), v, delta=0.0))
        np.testing.assert_allclose(out, v, rtol=1e-6)


class TestHostTomographyTwin:
    """Eager CPU-backend tomography routes through the numpy twin
    (`_host_real_tomography`); these pin that the twin and the XLA kernel
    draw from the same error distribution and that traced calls stay on
    the XLA path."""

    @pytest.mark.slow
    def test_error_distribution_matches_xla(self, key):
        from sq_learn_tpu.ops.quantum.tomography import (_tomography_unit,
                                                         real_tomography,
                                                         tomography)

        d, delta = 64, 0.2
        v = jnp.asarray(random_unit(7, d))
        # host twin errors (the eager dispatcher on the CPU conftest)
        errs_h = []
        errs_x = []
        for s in range(12):
            k = jax.random.PRNGKey(100 + s)
            errs_h.append(float(jnp.linalg.norm(tomography(k, v, delta) - v)))
            # the jit'd unit kernel is the XLA path regardless of backend
            import functools
            core = jax.jit(functools.partial(
                _tomography_unit,
                N=tomography_n_measurements(d, delta, "L2")))
            errs_x.append(float(jnp.linalg.norm(core(k, v) - v)))
        # both within the delta bound, and on the same error scale
        assert max(errs_h) <= delta and max(errs_x) <= delta
        m_h, m_x = np.mean(errs_h), np.mean(errs_x)
        assert 0.5 * m_x <= m_h <= 2.0 * m_x

    def test_traced_calls_stay_on_xla_path(self, key):
        from sq_learn_tpu.ops.quantum import tomography

        v = jnp.asarray(random_unit(5, 16))
        # tracing through jit must not touch the host twin (numpy would
        # raise a TracerArrayConversionError if it did)
        out = jax.jit(lambda k, x: tomography(k, x, 0.3))(key, v)
        assert float(jnp.linalg.norm(out - v)) <= 0.35

    def test_zero_vector_degrades_to_nan(self, key):
        from sq_learn_tpu.ops.quantum import tomography

        out = np.asarray(tomography(key, jnp.zeros(6), 0.2))
        assert out.shape == (6,) and np.isnan(out).all()
