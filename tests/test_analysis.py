"""sqcheck (``sq_learn_tpu.analysis``) — rules, baseline semantics,
knob-registry round-trip, docs generation, and the self-run gate
asserting the shipped tree is lint-clean against the committed
baseline."""

import json
import os
import subprocess
import sys

import pytest

from sq_learn_tpu import _knobs
from sq_learn_tpu.analysis import (
    Finding, load_baseline, run, get_rules, ALL_RULES)
from sq_learn_tpu.analysis.core import match_baseline
from sq_learn_tpu.analysis.docs import (
    check_docs, load_registry_module, render_knob_table, DOCS_RELPATH)
from sq_learn_tpu.analysis.selftest import FIXTURES, run_fixture

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------- rules

@pytest.mark.parametrize("rule_name", sorted(FIXTURES))
def test_rule_fires_on_bad_fixture(rule_name, tmp_path):
    bad, expected, _ = FIXTURES[rule_name]
    findings = run_fixture(rule_name, bad, base=str(tmp_path))
    assert findings, f"{rule_name} silent on its bad fixture"
    text = "\n".join(f.message for f in findings)
    for fragment in expected:
        assert fragment in text
    assert all(f.rule == rule_name for f in findings)


@pytest.mark.parametrize("rule_name", sorted(FIXTURES))
def test_rule_quiet_on_good_fixture(rule_name, tmp_path):
    _, _, good = FIXTURES[rule_name]
    findings = run_fixture(rule_name, good, base=str(tmp_path))
    # the shared fixture registry carries one intentionally-dead knob
    # (exercised by the knob-registry bad case)
    real = [f for f in findings if "SQ_DEAD" not in f.message]
    assert real == [], [f.message for f in real]


def test_all_rules_have_selftest_fixtures():
    assert {r.name for r in ALL_RULES} == set(FIXTURES)


def test_unknown_rule_name_raises():
    with pytest.raises(KeyError):
        get_rules(["no-such-rule"])


def test_syntax_error_is_reported_not_raised(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    findings, errors = run([str(bad)], get_rules(["rng-discipline"]),
                           root=str(tmp_path))
    assert findings == []
    assert len(errors) == 1 and "broken.py" in errors[0]


# ------------------------------------------------------------- baseline

def _mk(rule, path, message):
    return Finding(rule, path, 7, message)


def test_baseline_split_fresh_suppressed_stale():
    findings = [_mk("r1", "a.py", "m1"), _mk("r1", "a.py", "m2")]
    baseline = [
        {"rule": "r1", "path": "a.py", "message": "m1",
         "justification": "known"},
        {"rule": "r9", "path": "gone.py", "message": "old",
         "justification": "stale"},
    ]
    fresh, suppressed, stale = match_baseline(findings, baseline)
    assert [f.message for f in fresh] == ["m2"]
    assert [f.message for f in suppressed] == ["m1"]
    assert [e["message"] for e in stale] == ["old"]


def test_baseline_key_is_line_free():
    # two findings at different lines share one baseline entry
    findings = [_mk("r", "p.py", "m"),
                Finding("r", "p.py", 99, "m")]
    baseline = [{"rule": "r", "path": "p.py", "message": "m",
                 "justification": "both"}]
    fresh, suppressed, stale = match_baseline(findings, baseline)
    assert fresh == [] and stale == [] and len(suppressed) == 2


def test_load_baseline_rejects_missing_justification(tmp_path):
    p = tmp_path / "b.json"
    p.write_text(json.dumps(
        [{"rule": "r", "path": "p", "message": "m"}]))
    with pytest.raises(ValueError):
        load_baseline(str(p))


def test_committed_baseline_entries_are_justified():
    entries = load_baseline(os.path.join(
        REPO, "sq_learn_tpu", "analysis", "baseline.json"))
    assert 0 < len(entries) <= 10  # acceptance ceiling
    for e in entries:
        assert e["justification"] and "TODO" not in e["justification"]


# -------------------------------------------------- knob registry

def test_registry_round_trip(monkeypatch):
    monkeypatch.setenv("SQ_OOC_SHARD_BYTES", "1024")
    assert _knobs.get_int("SQ_OOC_SHARD_BYTES") == 1024
    monkeypatch.delenv("SQ_OOC_SHARD_BYTES")
    assert _knobs.get_int("SQ_OOC_SHARD_BYTES") == (8 << 20)  # registry
    assert _knobs.get_int("SQ_OOC_SHARD_BYTES", 5) == 5  # caller default


def test_flag_semantics(monkeypatch):
    # default-off flag: only "1" enables
    monkeypatch.delenv("SQ_OBS", raising=False)
    assert _knobs.get_bool("SQ_OBS") is False
    monkeypatch.setenv("SQ_OBS", "1")
    assert _knobs.get_bool("SQ_OBS") is True
    monkeypatch.setenv("SQ_OBS", "yes")
    assert _knobs.get_bool("SQ_OBS") is False
    # default-on flag: only "0" disables
    monkeypatch.delenv("SQ_SERVE_CACHE", raising=False)
    assert _knobs.get_bool("SQ_SERVE_CACHE") is True
    monkeypatch.setenv("SQ_SERVE_CACHE", "0")
    assert _knobs.get_bool("SQ_SERVE_CACHE") is False


def test_unregistered_knob_read_raises():
    with pytest.raises(_knobs.UnknownKnobError):
        _knobs.get_raw("SQ_NOT_A_KNOB")


def test_family_resolution():
    e = _knobs.resolve("SQ_REGRESS_TOL_LATENCY")
    assert e is not None and e.name == "SQ_REGRESS_TOL_*"
    assert _knobs.resolve("SQ_NOPE") is None


def test_no_raw_env_reads_outside_registry():
    """The PR's conversion invariant, asserted directly: zero fresh
    knob-registry findings over the package."""
    findings, errors = run(
        [os.path.join(REPO, "sq_learn_tpu")],
        get_rules(["knob-registry"]), root=REPO)
    assert errors == []
    assert findings == [], [str(f) for f in findings]


# ------------------------------------------------------------ docs

def test_knob_table_render_and_drift_gate():
    mod = load_registry_module(REPO)
    rendered = render_knob_table(mod)
    with open(os.path.join(REPO, DOCS_RELPATH)) as fh:
        committed = fh.read()
    assert rendered == committed, (
        "docs/knobs.md drifted — regenerate with "
        "`python -m sq_learn_tpu.analysis --docs > docs/knobs.md`")
    for k in mod.iter_knobs():
        assert f"`{k.name}`" in rendered


def test_check_docs_clean_at_head():
    assert check_docs(REPO) == []


def test_check_docs_flags_unregistered_token(tmp_path):
    root = tmp_path
    (root / "sq_learn_tpu").mkdir()
    src = open(os.path.join(
        REPO, "sq_learn_tpu", "_knobs.py")).read()
    (root / "sq_learn_tpu" / "_knobs.py").write_text(src)
    (root / "CLAUDE.md").write_text("set SQ_IMAGINARY_KNOB=1 to win\n")
    problems = check_docs(str(root))
    assert any("SQ_IMAGINARY_KNOB" in p for p in problems)


# --------------------------------------------------------- self-run

def test_shipped_tree_is_lint_clean():
    """`make lint`'s core contract: the committed tree + committed
    baseline produce zero fresh and zero stale findings."""
    baseline = load_baseline(os.path.join(
        REPO, "sq_learn_tpu", "analysis", "baseline.json"))
    findings, errors = run(
        [os.path.join(REPO, "sq_learn_tpu")], get_rules(), root=REPO)
    assert errors == []
    fresh, _suppressed, stale = match_baseline(findings, baseline)
    assert fresh == [], [str(f) for f in fresh]
    assert stale == [], [e["message"] for e in stale]


def test_obs_schema_record_types_export():
    from sq_learn_tpu.obs import schema
    assert isinstance(schema.RECORD_TYPES, tuple)
    assert "counter" in schema.RECORD_TYPES
    assert len(schema.RECORD_TYPES) == len(set(schema.RECORD_TYPES))


def test_cli_exit_codes(tmp_path):
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop("JAX_PLATFORMS", None)
    ok = subprocess.run(
        [sys.executable, "-m", "sq_learn_tpu.analysis",
         "--root", REPO], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=300)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = tmp_path / "bad.py"
    bad.write_text("import os\nV = os.environ.get('SQ_X')\n")
    red = subprocess.run(
        [sys.executable, "-m", "sq_learn_tpu.analysis",
         "--root", REPO, "--no-baseline", str(bad)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert red.returncode == 1, red.stdout + red.stderr
    assert "raw environment read" in red.stdout
