"""Checkpoint / resume tests (SURVEY §5: fitted-state serialization +
mid-run Lloyd state recovery)."""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sq_learn_tpu.datasets import make_blobs
from sq_learn_tpu.models import KMeans, MiniBatchQKMeans, QPCA
from sq_learn_tpu.utils import (
    load_estimator,
    load_pytree,
    save_estimator,
    save_pytree,
)


@pytest.fixture(scope="module")
def blobs():
    return make_blobs(n_samples=300, centers=3, n_features=6,
                      cluster_std=0.5, random_state=11)


def test_estimator_roundtrip_kmeans(tmp_path, blobs):
    X, _ = blobs
    km = KMeans(n_clusters=3, n_init=2, random_state=0).fit(X)
    path = save_estimator(km, str(tmp_path / "km"))
    km2 = load_estimator(path)
    assert type(km2) is KMeans
    np.testing.assert_allclose(km2.cluster_centers_, km.cluster_centers_)
    np.testing.assert_array_equal(km2.labels_, km.labels_)
    assert km2.inertia_ == pytest.approx(km.inertia_)
    # loaded estimator predicts without refit
    np.testing.assert_array_equal(km2.predict(X[:20]), km.predict(X[:20]))


def test_checkpoint_digest_and_format_version(tmp_path, blobs):
    """v2 checkpoints carry a content digest + format version; a
    tampered state.npz is refused on load, as is a future format."""
    import json

    X, _ = blobs
    km = KMeans(n_clusters=3, n_init=2, random_state=0).fit(X)
    path = save_estimator(km, str(tmp_path / "km_digest"))
    meta = json.load(open(tmp_path / "km_digest" / "meta.json"))
    assert meta["format_version"] == 2
    assert len(meta["state_digest"]) == 8
    load_estimator(path)  # clean digest verifies

    # flip one byte of the fitted state behind the manifest's back
    state = tmp_path / "km_digest" / "state.npz"
    blob = bytearray(state.read_bytes())
    blob[-1] ^= 0xFF
    state.write_bytes(bytes(blob))
    with pytest.raises(ValueError, match="stale or corrupt"):
        load_estimator(path)
    state.write_bytes(bytes(blob[:-1] + bytearray([blob[-1] ^ 0xFF])))

    # a FUTURE format version must be refused, not misread
    meta["format_version"] = 99
    json.dump(meta, open(tmp_path / "km_digest" / "meta.json", "w"))
    with pytest.raises(ValueError, match="format_version"):
        load_estimator(path)

    # v1 checkpoints (no digest/version keys) still load
    for k in ("format_version", "state_digest"):
        meta.pop(k)
    json.dump(meta, open(tmp_path / "km_digest" / "meta.json", "w"))
    km2 = load_estimator(path)
    np.testing.assert_allclose(km2.cluster_centers_, km.cluster_centers_)


def test_estimator_roundtrip_qpca(tmp_path, blobs):
    X, _ = blobs
    p = QPCA(n_components=3, random_state=0).fit(X)
    p2 = load_estimator(save_estimator(p, str(tmp_path / "qpca")))
    np.testing.assert_allclose(p2.components_, p.components_, rtol=1e-6)
    np.testing.assert_allclose(p2.transform(X[:5]), p.transform(X[:5]),
                               rtol=1e-5)


def test_partial_fit_resume_across_checkpoint(tmp_path, blobs):
    """The streaming-state API survives save/load mid-stream."""
    X, y = blobs
    mb = MiniBatchQKMeans(n_clusters=3, random_state=0)
    rng = np.random.default_rng(0)
    for _ in range(10):
        mb.partial_fit(X[rng.choice(len(X), 64, replace=False)])
    path = save_estimator(mb, str(tmp_path / "mb"))
    mb2 = load_estimator(path)
    np.testing.assert_allclose(mb2.cluster_centers_, mb.cluster_centers_)
    np.testing.assert_allclose(mb2.counts_, mb.counts_)
    for _ in range(10):
        mb2.partial_fit(X[rng.choice(len(X), 64, replace=False)])
    assert mb2.n_steps_ == 20


def test_pytree_roundtrip(tmp_path):
    key = jax.random.PRNGKey(0)
    tree = {"centers": jnp.arange(12.0).reshape(3, 4),
            "counts": jnp.ones(3),
            "key": jax.random.key_data(key)}
    f = str(tmp_path / "state.npz")
    save_pytree(f, tree, step=17)
    tree2, step = load_pytree(f, tree)
    assert step == 17
    np.testing.assert_allclose(tree2["centers"], tree["centers"])
    np.testing.assert_allclose(tree2["counts"], tree["counts"])


def test_pytree_structure_mismatch_raises(tmp_path):
    f = str(tmp_path / "state.npz")
    save_pytree(f, {"a": jnp.zeros(2)})
    with pytest.raises(ValueError, match="leaves"):
        load_pytree(f, {"a": jnp.zeros(2), "b": jnp.zeros(2)})


def test_estimator_roundtrip_knn(blobs):
    """KNN keeps its training data in trailing-underscore attrs so the
    checkpoint captures the full fitted state (regression: _X/_y were
    private and silently dropped)."""
    import tempfile

    from sq_learn_tpu.models import KNeighborsClassifier

    X, y = blobs
    knn = KNeighborsClassifier(n_neighbors=3).fit(X, y)
    with tempfile.TemporaryDirectory() as td:
        knn2 = load_estimator(save_estimator(knn, td))
    np.testing.assert_array_equal(knn2.predict(X[:25]), knn.predict(X[:25]))


def test_profiling_benchmark_and_timer():
    """Timer/benchmark block on device work (SURVEY §5 tracing layer)."""
    import jax.numpy as jnp

    from sq_learn_tpu.utils.profiling import Timer, benchmark

    def step(x):
        return (x @ x.T).sum()

    x = jnp.ones((64, 64))
    med, times = benchmark(step, x, repeats=3, warmup=1)
    assert med > 0 and len(times) == 3
    with Timer() as t:
        step(x)
    assert t.elapsed > 0


def test_profiling_flop_accounting(monkeypatch):
    """FLOP/MFU helpers (SURVEY §5; the roofline side of the bench
    suite's hardware-utilization evidence)."""
    from sq_learn_tpu.utils import profiling as prof

    assert prof.matmul_flops(64, 32, 16) == 2 * 64 * 32 * 16
    # one Lloyd iteration = E-step GEMM + M-step GEMM, 2·n·k·m each
    assert prof.lloyd_iter_flops(1000, 64, 10) == 4 * 1000 * 64 * 10
    # the CPU backend prices against the host-CPU peak estimate: finite
    # peak, finite MFU (pre-v2 both were None — bench_pallas_mfu reported
    # nothing useful off-TPU)
    monkeypatch.delenv("SQ_TPU_PEAK_FLOPS", raising=False)
    import numpy as np

    cpu_peak = prof.device_peak_flops()
    assert cpu_peak is not None and np.isfinite(cpu_peak) and cpu_peak > 0
    cpu_mfu = prof.mfu(1e9, 0.5)
    assert isinstance(cpu_mfu, float) and np.isfinite(cpu_mfu)
    assert cpu_mfu == (1e9 / 0.5) / cpu_peak
    # an unknown ACCELERATOR still gets no peak and no MFU claim

    class UnknownAccel:
        device_kind = "npu x1"
        platform = "axon"

    assert prof.device_peak_flops(UnknownAccel()) is None
    assert prof.mfu(1e12, 0.5, device=UnknownAccel()) is None
    # explicit override: MFU = achieved / peak
    monkeypatch.setenv("SQ_TPU_PEAK_FLOPS", "2e14")
    assert prof.device_peak_flops() == 2e14
    assert prof.mfu(1e14, 1.0) == 0.5
    # generation table keyed on device_kind
    class FakeDev:
        device_kind = "TPU v4"

    monkeypatch.delenv("SQ_TPU_PEAK_FLOPS", raising=False)
    assert prof.device_peak_flops(FakeDev()) == prof.TPU_PEAK_FLOPS["v4"]


# ---------------------------------------------------------------------------
# stream-state torn-write hardening (ISSUE 8 satellite): fsync-before-
# rename, .prev retention, and the corrupt-newest fallback
# ---------------------------------------------------------------------------


def _stream_tree():
    return {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
            "b": np.zeros((), np.int64)}


def test_stream_state_retains_prev_and_falls_back(tmp_path):
    from sq_learn_tpu.utils import load_stream_state, save_stream_state

    path = str(tmp_path / "ck.npz")
    t1 = _stream_tree()
    save_stream_state(path, t1, 4, "fp")
    t2 = {"a": t1["a"] + 1.0, "b": np.asarray(9, np.int64)}
    save_stream_state(path, t2, 8, "fp")
    assert os.path.exists(path) and os.path.exists(path + ".prev")
    tree, cursor = load_stream_state(path, _stream_tree(), "fp")
    assert cursor == 8
    np.testing.assert_array_equal(tree["a"], t2["a"])
    # truncate the newest (the torn-write shape): the retained .prev
    # must serve the pass instead of a cold start
    with open(path, "r+b") as fh:
        fh.truncate(12)
    tree, cursor = load_stream_state(path, _stream_tree(), "fp")
    assert cursor == 4
    np.testing.assert_array_equal(tree["a"], t1["a"])


def test_stream_state_kill_between_renames_window(tmp_path):
    """SIGKILL between the two os.replace calls leaves only ``.prev`` —
    the load must recover it."""
    from sq_learn_tpu.utils import load_stream_state, save_stream_state

    path = str(tmp_path / "ck.npz")
    save_stream_state(path, _stream_tree(), 3, "fp")
    os.replace(path, path + ".prev")  # simulate the torn window
    tree, cursor = load_stream_state(path, _stream_tree(), "fp")
    assert cursor == 3


def test_stream_state_mismatch_never_falls_back(tmp_path):
    """A COMPLETE newest checkpoint of a different pass is a different
    pass, not a torn write: no resurrection of the older .prev."""
    from sq_learn_tpu.utils import load_stream_state, save_stream_state

    path = str(tmp_path / "ck.npz")
    save_stream_state(path, _stream_tree(), 4, "fp-old")
    save_stream_state(path, _stream_tree(), 8, "fp-new")
    # .prev carries fp-old; the newest is complete but fp-different
    assert load_stream_state(path, _stream_tree(), "fp-old") is None


def test_stream_state_both_corrupt_cold_starts(tmp_path):
    from sq_learn_tpu.utils import load_stream_state, save_stream_state

    path = str(tmp_path / "ck.npz")
    save_stream_state(path, _stream_tree(), 4, "fp")
    save_stream_state(path, _stream_tree(), 8, "fp")
    for p in (path, path + ".prev"):
        with open(p, "wb") as fh:
            fh.write(b"garbage")
    assert load_stream_state(path, _stream_tree(), "fp") is None
