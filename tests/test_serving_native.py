"""Native dispatch fast path + cross-tenant megabatching (ISSUE 16).

The load-bearing claims: the native gather/scatter entry points are
byte-identical to the pure-Python path (so ``SQ_SERVE_NATIVE=0`` and a
host without a toolchain serve the same bits); pooled assembly buffers
never leak stale bytes between batches; same-fingerprint tenants
co-batch into one kernel launch with EXACT per-tenant attribution
(Σ per-tenant requests == run aggregate — the PR 12 reconciliation
gate); and the two opt-out knobs fall back to the PR 11 behavior.
All deterministic legs run ``background=False`` (submission-order
batching), so the parity claims are exact.
"""

import numpy as np
import pytest

from sq_learn_tpu import native, obs
from sq_learn_tpu.models import QKMeans, TruncatedSVD
from sq_learn_tpu.resilience import faults
from sq_learn_tpu.resilience.supervisor import breaker
from sq_learn_tpu.serving import MicroBatchDispatcher, ModelRegistry
from sq_learn_tpu.serving import cache as serve_cache


@pytest.fixture(scope="module")
def fitted():
    rng = np.random.default_rng(0)
    m = 12
    X = (rng.normal(size=(400, m))
         + 5.0 * rng.integers(0, 3, size=(400, 1))).astype(np.float32)
    qkm = QKMeans(n_clusters=3, random_state=0, n_init=1).fit(X)
    svd = TruncatedSVD(n_components=3, random_state=0).fit(X)
    return {"X": X, "m": m, "qkm": qkm, "svd": svd}


@pytest.fixture(autouse=True)
def _serving_hygiene():
    serve_cache.clear()
    yield
    serve_cache.clear()
    faults.disarm()
    breaker.reset("test teardown")
    if obs.enabled():
        obs.disable()


def _requests(fitted, n=24, sizes=(1, 5, 17, 40), seed=7):
    rng = np.random.default_rng(seed)
    return [rng.normal(size=(sizes[i % len(sizes)], fitted["m"]))
            .astype(np.float32) for i in range(n)]


# -- native gather/scatter bit parity ----------------------------------------


def test_serve_gather_scatter_bit_parity():
    """Native vs manual-numpy byte equality across shapes × dtypes ×
    bucket boundaries, including a stale (pooled) destination buffer and
    the exactly-full bucket."""
    rng = np.random.default_rng(3)
    dtypes = [np.float32, np.float64, np.int8, np.uint32]
    size_sets = [[1], [3, 5, 1], [8], [2, 2, 2, 2], [7, 1]]
    for dtype in dtypes:
        for sizes in size_sets:
            total = sum(sizes)
            for bucket in (total, 1 << (total - 1).bit_length() or 1):
                m = 6
                blocks = [rng.integers(0, 100, (s, m)).astype(dtype)
                          for s in sizes]
                # stale destination: the pool hands back used buffers
                got = np.full((bucket, m), 111, dtype)
                native.serve_gather(blocks, got)
                ref = np.zeros((bucket, m), dtype)
                off = 0
                for b in blocks:
                    ref[off:off + b.shape[0]] = b
                    off += b.shape[0]
                assert got.tobytes() == ref.tobytes(), (dtype, sizes,
                                                        bucket)
                # the dispatcher's trusted fast path (precomputed
                # addresses + counts) must write the same bytes
                got2 = np.full((bucket, m), 55, dtype)
                native.serve_gather(blocks, got2,
                                    addrs=[b.ctypes.data for b in blocks],
                                    counts=[b.shape[0] for b in blocks],
                                    trusted=True)
                assert got2.tobytes() == ref.tobytes()
                # scatter: 2D result and 1D result (predict labels),
                # default one-copy route AND the forced C route
                for src in (rng.integers(0, 9, (bucket, 4)).astype(dtype),
                            rng.integers(0, 9, (bucket,)).astype(dtype)):
                    for via_native in (False, True):
                        outs = native.serve_scatter(
                            src, sizes, via_native=via_native)
                        off = 0
                        for o, s in zip(outs, sizes):
                            legacy = np.array(src[off:off + s], copy=True)
                            off += s
                            assert o.dtype == legacy.dtype
                            assert o.shape == legacy.shape
                            assert o.flags.c_contiguous
                            assert o.tobytes() == legacy.tobytes()


def test_serve_gather_rejects_mismatch():
    out = np.zeros((8, 4), np.float32)
    with pytest.raises(ValueError):
        native.serve_gather([np.zeros((2, 5), np.float32)], out)
    with pytest.raises(ValueError):
        native.serve_gather([np.zeros((2, 4), np.float64)], out)
    with pytest.raises(ValueError):
        native.serve_gather([np.zeros((9, 4), np.float32)], out)
    with pytest.raises(ValueError):
        native.serve_scatter(np.zeros((4, 2), np.float32), [3, 2])


# -- dispatcher-level bit identity across the knob matrix --------------------


def _serve_all(reg, reqs, tenants_ops, **kw):
    """Serve the request list round-robin over (tenant, op) pairs on a
    fresh deterministic dispatcher; returns the responses + the closed
    dispatcher's aggregate summary + the dispatcher itself."""
    serve_cache.clear()
    d = MicroBatchDispatcher(reg, background=False, max_batch_rows=64,
                             **kw)
    futs = []
    for i, r in enumerate(reqs):
        t, op = tenants_ops[i % len(tenants_ops)]
        futs.append(d.submit(t, op, r))
    d.flush()
    outs = [f.result(timeout=30) for f in futs]
    slo = d.close()
    return outs, slo, d


def test_native_off_bit_identical_responses(fitted):
    """SQ_SERVE_NATIVE=0 (the PR 11 per-request numpy path) and the
    native pooled path serve bit-identical bytes — exact AND quantized
    routes, across several flush cycles so pooled buffers get reused."""
    reg = ModelRegistry()
    reg.register("a", fitted["qkm"])
    reg.register("b", fitted["svd"])
    reg.register("qa", fitted["qkm"], quantize="bf16")
    reg.register("ia", fitted["qkm"], quantize="int8")
    mix = [("a", "predict"), ("b", "transform"), ("qa", "predict"),
           ("ia", "transform"), ("a", "transform")]
    reqs = _requests(fitted, n=40)
    on, slo_on, _ = _serve_all(reg, reqs, mix, native=True)
    off, slo_off, _ = _serve_all(reg, reqs, mix, native=False)
    assert len(on) == len(off) == len(reqs)
    for x, y in zip(on, off):
        assert x.dtype == y.dtype and x.shape == y.shape
        assert x.tobytes() == y.tobytes()
    assert slo_on["requests"] == slo_off["requests"] == len(reqs)
    assert slo_on["batches"] == slo_off["batches"]


def test_native_knob_and_megabatch_knob_latch(monkeypatch, fitted):
    reg = ModelRegistry()
    reg.register("a", fitted["qkm"])
    monkeypatch.setenv("SQ_SERVE_NATIVE", "0")
    monkeypatch.setenv("SQ_SERVE_MEGABATCH", "0")
    d = MicroBatchDispatcher(reg, background=False)
    assert d._native is False and d._megabatch is False
    d.close()
    monkeypatch.delenv("SQ_SERVE_NATIVE")
    monkeypatch.delenv("SQ_SERVE_MEGABATCH")
    d = MicroBatchDispatcher(reg, background=False)
    assert d._native is True and d._megabatch is True
    d.close()


def test_degraded_route_native_bit_equal(fitted, monkeypatch):
    """An OPEN breaker degrades the batch to the host route reusing the
    SAME pooled, natively-assembled payload — responses stay bit-equal
    to the supervised run."""
    reg = ModelRegistry()
    reg.register("a", fitted["qkm"])
    reqs = _requests(fitted, n=12)
    clean, slo_clean, _ = _serve_all(reg, reqs, [("a", "predict")],
                                     native=True)
    assert slo_clean["degraded"] == 0
    monkeypatch.setenv("SQ_BREAKER_COOLDOWN_S", "3600")
    breaker.reset("test setup")
    for _ in range(3):
        breaker.record_failure("test wedge")
    assert breaker.state() == "open"
    degraded, slo_deg, _ = _serve_all(reg, reqs, [("a", "predict")],
                                      native=True)
    breaker.reset("test: degrade leg done")
    assert slo_deg["degraded"] >= 1
    assert all(np.array_equal(a, b) for a, b in zip(clean, degraded))


# -- cross-tenant megabatching ----------------------------------------------


def test_megabatch_cobatches_same_fingerprint_tenants(fitted):
    """Two tenants registered from the same estimator share a
    fingerprint; their interleaved requests coalesce into shared
    launches (``megabatches() >= 1``) and every response is row-aligned
    with its own request — per-tenant scatter isolation."""
    reg = ModelRegistry()
    reg.register("alpha", fitted["qkm"])
    reg.register("beta", fitted["qkm"])
    reqs = _requests(fitted, n=24)
    outs, slo, d = _serve_all(reg, reqs, [("alpha", "predict"),
                                          ("beta", "predict")])
    assert d.megabatches() >= 1
    assert slo["batches"] < len(reqs)
    qkm = fitted["qkm"]
    for r, o in zip(reqs, outs):
        assert np.array_equal(o, qkm.predict(r))


def test_megabatch_off_is_tenant_scoped_and_bit_identical(fitted):
    """SQ_SERVE_MEGABATCH=0 prefixes the group key with the tenant:
    equal-fingerprint tenants never share a launch, and responses stay
    bit-identical to the megabatched run (same params by construction)."""
    reg = ModelRegistry()
    reg.register("alpha", fitted["qkm"])
    reg.register("beta", fitted["qkm"])
    reqs = _requests(fitted, n=24)
    mix = [("alpha", "predict"), ("beta", "predict")]
    mega, slo_mega, d_mega = _serve_all(reg, reqs, mix, megabatch=True)
    solo, slo_solo, d_solo = _serve_all(reg, reqs, mix, megabatch=False)
    assert d_mega.megabatches() >= 1
    assert d_solo.megabatches() == 0
    # tenant-scoped batching really split the launches
    assert slo_solo["batches"] > slo_mega["batches"]
    for x, y in zip(mega, solo):
        assert x.tobytes() == y.tobytes()


def test_quantized_and_exact_tenants_never_merge(fitted):
    """A bf16 tenant and an exact-f32 tenant of the same estimator have
    different fingerprints (quantize mode suffix) AND transfer dtypes —
    they must never land in one launch."""
    reg = ModelRegistry()
    reg.register("exact", fitted["qkm"])
    reg.register("quant", fitted["qkm"], quantize="bf16")
    reqs = _requests(fitted, n=16)
    _, _, d = _serve_all(reg, reqs, [("exact", "predict"),
                                     ("quant", "predict")])
    assert d.megabatches() == 0


def test_megabatch_per_tenant_attribution_reconciles(fitted):
    """The honesty gate: under an active recorder a megabatched run's
    per-tenant slo records sum EXACTLY to the run aggregate (requests),
    each tenant's stages/bytes are its own share, and the
    ``serving.megabatches`` counter lands in the artifact."""
    reg = ModelRegistry()
    reg.register("alpha", fitted["qkm"], slo_p99_ms=10_000.0)
    reg.register("beta", fitted["qkm"], slo_p99_ms=20_000.0)
    obs.enable()
    reqs = _requests(fitted, n=30)
    outs, slo, d = _serve_all(reg, reqs, [("alpha", "predict"),
                                          ("beta", "predict"),
                                          ("alpha", "predict")])
    tenants = d.slo.tenant_summaries()
    rec = obs.disable()
    assert d.megabatches() >= 1
    assert set(tenants) == {"alpha", "beta"}
    assert sum(t["requests"] for t in tenants.values()) == slo["requests"]
    assert sum(t["transfer_bytes"] for t in tenants.values()) \
        <= slo["transfer_bytes"]
    # each tenant burned against its OWN declared target
    assert tenants["alpha"]["targets"]["p99_ms"] == 10_000.0
    assert tenants["beta"]["targets"]["p99_ms"] == 20_000.0
    # stage decomposition present per tenant and sums to ~the aggregate
    # (each summarize() rounds to 1e-6, so allow a few ulps of that)
    for key in ("assemble", "transfer", "compute", "scatter", "queue"):
        agg = slo["stages"][key]
        split = sum(t["stages"].get(key, 0.0) for t in tenants.values())
        assert abs(split - agg) <= 1e-5, (key, split, agg)
    assert rec.counters.get("serving.megabatches", 0) == d.megabatches()
    # the error-budget ledger billed each tenant its OWN rows and the
    # run-scoped counts reconcile too
    led = d.budget_ledger()
    assert led is not None
    assert {"alpha", "beta"} <= set(led.tenants())
    assert sum(led.total_requests(t) for t in ("alpha", "beta")) \
        == slo["requests"]


def test_submit_many_burst_shares_stamp_and_reconciles(fitted):
    """The burst path (one clock stamp, one resolve per tenant, pre-
    sized subqueue extends) still answers every request correctly and
    keeps the SLO request count exact."""
    reg = ModelRegistry()
    reg.register("alpha", fitted["qkm"])
    reg.register("beta", fitted["qkm"])
    reqs = _requests(fitted, n=20)
    d = MicroBatchDispatcher(reg, background=False, max_batch_rows=64)
    burst = [("alpha" if i % 2 else "beta", "predict", r)
             for i, r in enumerate(reqs)]
    futs = d.submit_many(burst)
    d.flush()
    outs = [f.result(timeout=30) for f in futs]
    slo = d.close()
    qkm = fitted["qkm"]
    for (_, _, r), o in zip(burst, outs):
        assert np.array_equal(o, qkm.predict(r))
    assert slo["requests"] == len(reqs)
    assert d.megabatches() >= 1
