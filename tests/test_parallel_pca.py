"""Data-parallel centered SVD (parallel/pca.py) vs the single-device path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sq_learn_tpu.models import QPCA
from sq_learn_tpu.ops.linalg import centered_svd
from sq_learn_tpu.parallel import centered_svd_sharded, make_mesh


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(jax.devices("cpu")[:8])


@pytest.mark.parametrize("n", [160, 103])  # even and uneven shards
def test_matches_single_device(mesh, n):
    X = np.random.default_rng(0).normal(size=(n, 12)).astype(np.float32)
    mean_s, U_s, S_s, Vt_s = centered_svd_sharded(mesh, X)
    mean, U, S, Vt = centered_svd(X, method="gram")
    np.testing.assert_allclose(np.asarray(mean_s), np.asarray(mean),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(S_s), np.asarray(S),
                               rtol=1e-4, atol=1e-3)
    # deterministic signs (svd_flip) -> factors comparable directly
    np.testing.assert_allclose(np.asarray(Vt_s), np.asarray(Vt),
                               rtol=1e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(U_s), np.asarray(U),
                               rtol=1e-3, atol=2e-3)
    # U really is row-sharded over the mesh
    assert len(U_s.sharding.device_set) == 8


def test_reconstruction(mesh):
    X = np.random.default_rng(1).normal(size=(75, 6)).astype(np.float32)
    mean, U, S, Vt = centered_svd_sharded(mesh, X)
    Xc = X - np.asarray(mean)
    np.testing.assert_allclose(
        np.asarray(U) @ np.diag(np.asarray(S)) @ np.asarray(Vt), Xc,
        rtol=1e-3, atol=1e-3)


class TestQPCAMesh:
    def test_classical_fit_parity(self, mesh):
        X = np.random.default_rng(2).normal(size=(120, 10)).astype(np.float32)
        ref = QPCA(n_components=4, svd_solver="full").fit(X)
        dp = QPCA(n_components=4, svd_solver="full", mesh=mesh).fit(X)
        np.testing.assert_allclose(dp.explained_variance_,
                                   ref.explained_variance_, rtol=1e-4)
        np.testing.assert_allclose(dp.components_, ref.components_,
                                   rtol=1e-3, atol=2e-3)
        np.testing.assert_allclose(dp.left_sv, ref.left_sv,
                                   rtol=1e-3, atol=2e-3)
        np.testing.assert_allclose(dp.transform(X), ref.transform(X),
                                   rtol=1e-3, atol=2e-3)

    def test_quantum_fit_on_mesh(self, mesh):
        X = np.random.default_rng(3).normal(size=(96, 8)).astype(np.float32)
        est = QPCA(n_components=4, svd_solver="full", mesh=mesh,
                   random_state=0)
        est.fit(X, estimate_all=True, delta=0.1, eps=0.1, theta_major=0.5)
        assert est.estimate_right_sv.shape[1] == X.shape[1]
        assert np.all(np.isfinite(est.estimate_s_values))


def test_wide_input_thin_spectrum(mesh):
    # n < m: the mesh path must return the thin min(n, m) spectrum, not m
    # structural eigenvalues (noise_variance_/all_* parity with the
    # single-device fit)
    X = np.random.default_rng(4).normal(size=(40, 96)).astype(np.float32)
    ref = QPCA(n_components=10, svd_solver="full").fit(X)
    dp = QPCA(n_components=10, svd_solver="full", mesh=mesh).fit(X)
    assert dp.all_singular_values_.shape == ref.all_singular_values_.shape
    np.testing.assert_allclose(dp.noise_variance_, ref.noise_variance_,
                               rtol=1e-3)
    np.testing.assert_allclose(dp.explained_variance_,
                               ref.explained_variance_, rtol=1e-3)


def test_mesh_forces_full_solver(mesh):
    # 'auto' on a large-sample input would pick 'randomized' — under a mesh
    # that would silently run single-device; the mesh must force 'full'
    X = np.random.default_rng(5).normal(size=(900, 50)).astype(np.float32)
    dp = QPCA(n_components=5, mesh=mesh).fit(X)
    assert dp._fit_svd_solver == "full"
    with pytest.raises(ValueError, match="mesh requires svd_solver"):
        QPCA(n_components=5, svd_solver="randomized", mesh=mesh).fit(X)


class TestTomographySharded:
    """Row-sharded tomography (the quantum-transform side of pod-scale
    qPCA, VERDICT r4 next #7)."""

    def test_bit_identical_to_xla_path_on_one_device(self):
        from sq_learn_tpu.ops.quantum.tomography import (
            tomography, tomography_n_measurements)
        from sq_learn_tpu.parallel import tomography_sharded

        A = np.random.default_rng(0).normal(size=(16, 6)).astype(np.float32)
        noise = 0.4
        N = tomography_n_measurements(A.shape[1], noise, "L2")
        mesh1 = make_mesh(jax.devices("cpu")[:1])
        key = jax.random.PRNGKey(7)
        sharded = tomography_sharded(mesh1, key, A, noise)
        # jit forces the direct call down the same XLA sampler (an eager
        # CPU call would route through the host twin's different stream)
        direct = jax.jit(
            lambda k, a: tomography(k, a, noise, true_tomography=True,
                                    N=N))(key, jnp.asarray(A))
        np.testing.assert_array_equal(np.asarray(sharded),
                                      np.asarray(direct))

    def test_mesh_noise_bounded_and_engaged(self, mesh):
        from sq_learn_tpu.parallel import tomography_sharded

        # 13 rows over 8 devices: padding rows exercised (they must not
        # leak NaN through the per-row normalization guard)
        A = np.random.default_rng(1).normal(size=(13, 8)).astype(np.float32)
        noise = 0.3
        est = np.asarray(tomography_sharded(
            mesh, jax.random.PRNGKey(3), A, noise))
        assert est.shape == A.shape
        assert np.all(np.isfinite(est))
        err = np.linalg.norm(est - A, axis=1)
        assert err.max() > 0.0
        assert err.max() < 3.0 * noise * np.linalg.norm(A, axis=1).max()

    def test_zero_noise_short_circuits_exact(self, mesh):
        from sq_learn_tpu.parallel import tomography_sharded

        A = np.random.default_rng(2).normal(size=(24, 5)).astype(np.float32)
        out = np.asarray(tomography_sharded(
            mesh, jax.random.PRNGKey(0), A, 0.0))
        np.testing.assert_array_equal(out, A)

    def test_qpca_mesh_quantum_transform(self, mesh):
        X = np.random.default_rng(3).normal(size=(67, 8)).astype(np.float32)
        est = QPCA(n_components=4, mesh=mesh, random_state=0).fit(X)
        Z = est.transform(X)
        out = est.transform(X, classic_transform=False,
                            quantum_representation=True, epsilon_delta=0.5,
                            norm="None", psi=0.5)
        Zq = np.asarray(out["quantum_representation_results"])
        assert Zq.shape == Z.shape
        err = np.linalg.norm(Zq - Z, axis=1)
        assert 0.0 < err.max() < 3.0 * 0.5 * max(
            np.linalg.norm(Z, axis=1).max(), 1.0)


class TestUncenteredSVDSharded:
    """Sample-sharded LSA SVD (TruncatedSVD's mesh engine)."""

    @pytest.mark.parametrize("n", [160, 103])  # even and uneven shards
    def test_matches_exact_thin_svd(self, mesh, n):
        from sq_learn_tpu.ops.linalg import svd_flip_v, thin_svd
        from sq_learn_tpu.parallel import uncentered_svd_sharded

        X = np.random.default_rng(7).normal(size=(n, 12)).astype(np.float32)
        U_s, S_s, Vt_s = uncentered_svd_sharded(mesh, X)
        U, S, Vt = thin_svd(jnp.asarray(X))
        U, Vt = svd_flip_v(U, Vt)
        np.testing.assert_allclose(np.asarray(S_s), np.asarray(S),
                                   rtol=1e-4, atol=1e-3)
        np.testing.assert_allclose(np.asarray(Vt_s), np.asarray(Vt),
                                   rtol=1e-3, atol=2e-3)
        np.testing.assert_allclose(np.asarray(U_s), np.asarray(U),
                                   rtol=1e-3, atol=2e-3)

    def test_truncated_svd_mesh_matches_exact(self, mesh):
        from sq_learn_tpu.models import TruncatedSVD

        X = np.random.default_rng(8).normal(size=(91, 15)).astype(np.float32)
        exact = TruncatedSVD(n_components=5, algorithm="arpack").fit(X)
        meshed = TruncatedSVD(n_components=5, mesh=mesh).fit(X)
        np.testing.assert_allclose(meshed.singular_values_,
                                   exact.singular_values_, rtol=1e-4)
        np.testing.assert_allclose(meshed.components_, exact.components_,
                                   rtol=1e-3, atol=2e-3)
        np.testing.assert_allclose(meshed.transform(X), exact.transform(X),
                                   rtol=1e-3, atol=2e-3)
        np.testing.assert_allclose(
            meshed.explained_variance_ratio_,
            exact.explained_variance_ratio_, rtol=1e-3, atol=1e-4)

    def test_truncated_svd_mesh_warns_on_explicit_arpack(self, mesh):
        from sq_learn_tpu.models import TruncatedSVD

        X = np.random.default_rng(9).normal(size=(64, 10)).astype(np.float32)
        with pytest.warns(RuntimeWarning, match="Gram route"):
            TruncatedSVD(n_components=3, algorithm="arpack",
                         mesh=mesh).fit(X)


def _assert_separated_rows_match(S, Vt_got, Vt_want, gap=5e-2, tol=5e-2):
    """Compare right-singular rows (sign included) only where the
    spectrum is well-separated relative to ``gap``."""
    S = np.abs(S)
    scale = max(float(S[0]), 1e-12)
    for i in range(len(S)):
        near = [abs(S[i] - S[j]) for j in (i - 1, i + 1)
                if 0 <= j < len(S)]
        if min(near) / scale < gap or S[i] / scale < gap:
            continue
        np.testing.assert_allclose(Vt_got[i], Vt_want[i],
                                   rtol=tol, atol=tol,
                                   err_msg=f"sign/row mismatch at "
                                           f"component {i}")


@pytest.mark.slow
def test_sharded_gram_svd_fuzz_matches_single_device():
    """Randomized (n, m, n_devices) sweep over both centered and
    uncentered sharded SVDs vs their single-device twins — padding,
    thin-spectrum slicing (n < m and n > m), and sign conventions all
    exercised."""
    from sq_learn_tpu.ops.linalg import centered_svd, svd_flip_v, thin_svd
    from sq_learn_tpu.parallel import (centered_svd_sharded,
                                       uncentered_svd_sharded)

    rng = np.random.default_rng(13)
    for _ in range(10):
        ndev = int(rng.choice([1, 2, 4, 8]))
        sub = make_mesh(jax.devices("cpu")[:ndev])
        n = int(rng.integers(max(2, ndev), 200))
        m = int(rng.integers(2, 40))
        X = rng.normal(size=(n, m)).astype(np.float32)
        r = min(n, m)

        mean_s, U_s, S_s, Vt_s = centered_svd_sharded(sub, X)
        mean, U, S, Vt = centered_svd(X, method="gram")
        np.testing.assert_allclose(np.asarray(S_s), np.asarray(S),
                                   rtol=1e-3, atol=1e-2,
                                   err_msg=f"centered ndev={ndev} "
                                           f"n={n} m={m}")
        np.testing.assert_allclose(
            np.asarray(U_s) * np.asarray(S_s) @ np.asarray(Vt_s)
            + np.asarray(mean_s),
            X, rtol=1e-2, atol=1e-2)
        # the deterministic-sign contract (svd_flip_v), pinned directly —
        # but only on well-separated components: near-degenerate singular
        # pairs span an arbitrary rotation of the same subspace, where a
        # row-by-row comparison is meaningless for any implementation
        _assert_separated_rows_match(np.asarray(S_s), np.asarray(Vt_s),
                                     np.asarray(Vt))

        U_u, S_u, Vt_u = uncentered_svd_sharded(sub, X)
        Ur, Sr, Vtr = thin_svd(jnp.asarray(X))
        Ur, Vtr = svd_flip_v(Ur, Vtr)
        np.testing.assert_allclose(np.asarray(S_u), np.asarray(Sr)[:r],
                                   rtol=1e-3, atol=1e-2,
                                   err_msg=f"uncentered ndev={ndev} "
                                           f"n={n} m={m}")
        np.testing.assert_allclose(
            np.asarray(U_u) * np.asarray(S_u) @ np.asarray(Vt_u),
            X, rtol=1e-2, atol=1e-2)
        _assert_separated_rows_match(np.asarray(S_u), np.asarray(Vt_u),
                                     np.asarray(Vtr)[:r])
