"""Data-parallel centered SVD (parallel/pca.py) vs the single-device path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sq_learn_tpu.models import QPCA
from sq_learn_tpu.ops.linalg import centered_svd
from sq_learn_tpu.parallel import centered_svd_sharded, make_mesh


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(jax.devices("cpu")[:8])


@pytest.mark.parametrize("n", [160, 103])  # even and uneven shards
def test_matches_single_device(mesh, n):
    X = np.random.default_rng(0).normal(size=(n, 12)).astype(np.float32)
    mean_s, U_s, S_s, Vt_s = centered_svd_sharded(mesh, X)
    mean, U, S, Vt = centered_svd(X, method="gram")
    np.testing.assert_allclose(np.asarray(mean_s), np.asarray(mean),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(S_s), np.asarray(S),
                               rtol=1e-4, atol=1e-3)
    # deterministic signs (svd_flip) -> factors comparable directly
    np.testing.assert_allclose(np.asarray(Vt_s), np.asarray(Vt),
                               rtol=1e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(U_s), np.asarray(U),
                               rtol=1e-3, atol=2e-3)
    # U really is row-sharded over the mesh
    assert len(U_s.sharding.device_set) == 8


def test_reconstruction(mesh):
    X = np.random.default_rng(1).normal(size=(75, 6)).astype(np.float32)
    mean, U, S, Vt = centered_svd_sharded(mesh, X)
    Xc = X - np.asarray(mean)
    np.testing.assert_allclose(
        np.asarray(U) @ np.diag(np.asarray(S)) @ np.asarray(Vt), Xc,
        rtol=1e-3, atol=1e-3)


class TestQPCAMesh:
    def test_classical_fit_parity(self, mesh):
        X = np.random.default_rng(2).normal(size=(120, 10)).astype(np.float32)
        ref = QPCA(n_components=4, svd_solver="full").fit(X)
        dp = QPCA(n_components=4, svd_solver="full", mesh=mesh).fit(X)
        np.testing.assert_allclose(dp.explained_variance_,
                                   ref.explained_variance_, rtol=1e-4)
        np.testing.assert_allclose(dp.components_, ref.components_,
                                   rtol=1e-3, atol=2e-3)
        np.testing.assert_allclose(dp.left_sv, ref.left_sv,
                                   rtol=1e-3, atol=2e-3)
        np.testing.assert_allclose(dp.transform(X), ref.transform(X),
                                   rtol=1e-3, atol=2e-3)

    def test_quantum_fit_on_mesh(self, mesh):
        X = np.random.default_rng(3).normal(size=(96, 8)).astype(np.float32)
        est = QPCA(n_components=4, svd_solver="full", mesh=mesh,
                   random_state=0)
        est.fit(X, estimate_all=True, delta=0.1, eps=0.1, theta_major=0.5)
        assert est.estimate_right_sv.shape[1] == X.shape[1]
        assert np.all(np.isfinite(est.estimate_s_values))


def test_wide_input_thin_spectrum(mesh):
    # n < m: the mesh path must return the thin min(n, m) spectrum, not m
    # structural eigenvalues (noise_variance_/all_* parity with the
    # single-device fit)
    X = np.random.default_rng(4).normal(size=(40, 96)).astype(np.float32)
    ref = QPCA(n_components=10, svd_solver="full").fit(X)
    dp = QPCA(n_components=10, svd_solver="full", mesh=mesh).fit(X)
    assert dp.all_singular_values_.shape == ref.all_singular_values_.shape
    np.testing.assert_allclose(dp.noise_variance_, ref.noise_variance_,
                               rtol=1e-3)
    np.testing.assert_allclose(dp.explained_variance_,
                               ref.explained_variance_, rtol=1e-3)


def test_mesh_forces_full_solver(mesh):
    # 'auto' on a large-sample input would pick 'randomized' — under a mesh
    # that would silently run single-device; the mesh must force 'full'
    X = np.random.default_rng(5).normal(size=(900, 50)).astype(np.float32)
    dp = QPCA(n_components=5, mesh=mesh).fit(X)
    assert dp._fit_svd_solver == "full"
    with pytest.raises(ValueError, match="mesh requires svd_solver"):
        QPCA(n_components=5, svd_solver="randomized", mesh=mesh).fit(X)
