"""AOT-warmed serving contract tests (ISSUE 11, tentpole a).

The load-bearing ones: a warmed bucket ladder serves a mixed load with
ZERO serving-path jit compiles under a flat watchdog budget of 0 with
``SQ_OBS_STRICT=1`` armed (an excess compile would RAISE, failing the
test); executables are shared across tenants by abstract signature; and
an out-of-ladder shape falls back to the lazily-compiling jit wrapper
without losing the request.
"""

import numpy as np
import pytest

from sq_learn_tpu import obs
from sq_learn_tpu.models import QKMeans, TruncatedSVD
from sq_learn_tpu.serving import (MicroBatchDispatcher, ModelRegistry,
                                  aot, kernel_cache_sizes,
                                  pin_compile_budgets)
from sq_learn_tpu.serving import cache as serve_cache


@pytest.fixture(scope="module")
def fitted():
    rng = np.random.default_rng(0)
    m = 12
    X = (rng.normal(size=(400, m))
         + 5.0 * rng.integers(0, 3, size=(400, 1))).astype(np.float32)
    qkm = QKMeans(n_clusters=3, random_state=0, n_init=1).fit(X)
    svd = TruncatedSVD(n_components=3, random_state=0).fit(X)
    return {"X": X, "m": m, "qkm": qkm, "svd": svd}


@pytest.fixture(autouse=True)
def _aot_hygiene():
    aot.clear()
    serve_cache.clear()
    yield
    aot.clear()
    serve_cache.clear()
    if obs.enabled():
        obs.disable()


def test_bucket_ladder_covers_pow2_run_and_cap():
    assert aot.bucket_ladder(8, 512) == [8, 16, 32, 64, 128, 256, 512]
    # a non-pow2 cap still terminates the ladder (bucket_rows clamps
    # every in-cap batch to it)
    assert aot.bucket_ladder(8, 100) == [8, 16, 32, 64, 100]
    assert aot.bucket_ladder(16, 16) == [16]


def test_warm_then_zero_compiles_under_strict(fitted, monkeypatch):
    """The tentpole claim: after registry.warm, a mixed-size mixed-dtype
    load mints not one jit compile — pinned by flat budget 0 + strict
    mode, and by the jit caches' own entry counts."""
    monkeypatch.setenv("SQ_OBS_STRICT", "1")
    reg = ModelRegistry()
    reg.register("a", fitted["qkm"])
    reg.register("b", fitted["svd"])
    obs.enable()
    stats = reg.warm(buckets=aot.bucket_ladder(8, 64))
    assert stats == {"a": "loaded", "b": "loaded"}
    assert aot.cache_size() > 0
    before = kernel_cache_sizes()
    pin_compile_budgets(0)

    rng = np.random.default_rng(7)
    d = MicroBatchDispatcher(reg, background=False, max_batch_rows=64)
    for i, size in enumerate((1, 2, 5, 9, 17, 33, 40, 64)):
        rows = rng.normal(size=(size, fitted["m"]))
        rows = rows.astype(np.float32 if i % 2 else np.float64)
        out = d.serve("a", "predict", rows)
        assert np.array_equal(
            out, fitted["qkm"].predict(rows.astype(np.float32)))
        d.serve("b", "transform", rows)
    d.close()

    assert d.aot_stats()["misses"] == 0
    assert d.aot_stats()["hits"] > 0
    after = kernel_cache_sizes()
    assert after == before  # the jit caches never grew
    report = obs.watchdog.report()
    for name in ("serving.predict_centers", "serving.transform_centers",
                 "serving.transform_components"):
        assert report[name]["budget"] == 0
        assert report[name]["compiles"] == 0
    rec = obs.get_recorder()
    assert rec.counters.get("serving.aot_compiles", 0) == aot.cache_size()
    assert rec.counters.get("serving.aot_cache_hits", 0) > 0
    obs.disable()


def test_executables_shared_across_equal_shapes(fitted):
    """Two tenants with identical param shapes share one executable set
    — the cache keys on the abstract signature, not the tenant."""
    reg = ModelRegistry()
    reg.register("a", fitted["qkm"])
    reg.warm(["a"], buckets=[8, 16])
    minted = aot.cache_size()
    # same estimator under a second tenant: everything already warm
    reg.register("a2", fitted["qkm"])
    stats = aot.warm_model(reg.resolve("a2"), buckets=[8, 16])
    assert stats["compiled"] == 0
    assert stats["cached"] > 0
    assert aot.cache_size() == minted


def test_out_of_ladder_shape_falls_back_to_jit(fitted):
    """An oversized single request pads past max_batch_rows into a
    bucket the ladder never warmed: the dispatch must miss the AOT
    cache, compile lazily, and still answer correctly."""
    reg = ModelRegistry()
    reg.register("a", fitted["qkm"])
    reg.warm(["a"], buckets=aot.bucket_ladder(8, 64))
    d = MicroBatchDispatcher(reg, background=False, max_batch_rows=64)
    rows = np.random.default_rng(3).normal(
        size=(100, fitted["m"])).astype(np.float32)  # pads to 128
    out = d.serve("a", "predict", rows)
    d.close()
    assert np.array_equal(out, fitted["qkm"].predict(rows))
    assert d.aot_stats()["misses"] >= 1


def test_dispatcher_warm_uses_its_own_ladder(fitted):
    """dispatcher.warm() must warm THIS dispatcher's bucket config, not
    the env defaults — its smallest and largest buckets both resolve."""
    reg = ModelRegistry()
    reg.register("a", fitted["qkm"])
    d = MicroBatchDispatcher(reg, background=False, max_batch_rows=32,
                             min_bucket_rows=4)
    d.warm()
    model = reg.resolve("a")
    for bucket in (4, 8, 16, 32):
        assert aot.lookup(model, "predict", bucket,
                          np.dtype(np.float32)) is not None
    d.close()


def test_enable_persistent_cache_noop_without_dir(monkeypatch):
    monkeypatch.delenv("SQ_COMPILE_CACHE_DIR", raising=False)
    assert aot.enable_persistent_cache() is False


def test_warm_returns_cached_on_second_call(fitted):
    reg = ModelRegistry()
    reg.register("a", fitted["qkm"])
    model = reg.resolve("a")
    first = aot.warm_model(model, buckets=[8])
    second = aot.warm_model(model, buckets=[8])
    assert first["compiled"] == second["cached"]
    assert second["compiled"] == 0


def test_warm_captures_xla_cost_at_warm_time(fitted):
    """The cost accounting rides the warm's own lowering — records
    exist before any request is served."""
    reg = ModelRegistry()
    reg.register("a", fitted["qkm"])
    rec = obs.enable()
    reg.warm(["a"], buckets=[8, 16])
    sites = {r["site"] for r in rec.xla_cost_records}
    assert "serving.predict_centers" in sites
    assert all(isinstance(r.get("flops"), float)
               for r in rec.xla_cost_records
               if r["site"].startswith("serving."))
    obs.disable()
