"""Train-sharded k-NN search (parallel/neighbors.py) vs the single-device
path, plus the classifier's mesh dispatch."""

import numpy as np
import jax
import pytest

from sq_learn_tpu.models.neighbors import KNeighborsClassifier, knn_indices
from sq_learn_tpu.parallel import knn_indices_sharded, make_mesh


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(jax.devices("cpu")[:8])


@pytest.mark.parametrize("n,nq,k", [
    (256, 40, 5),    # even shards
    (101, 7, 10),    # uneven shards (padding rows in play)
    (20, 4, 10),     # k exceeds the per-shard row count
    (64, 5, 64),     # k == n_train (every row is a neighbor)
])
def test_matches_single_device(mesh, n, nq, k):
    rng = np.random.default_rng(3)
    Xt = rng.normal(size=(n, 11)).astype(np.float32)
    Xq = rng.normal(size=(nq, 11)).astype(np.float32)
    si, sd = knn_indices_sharded(mesh, Xt, Xq, k)
    ri, rd = knn_indices(Xt, Xq, k)
    # continuous random data: no exact distance ties, so indices must
    # agree exactly, not just up to tie order
    np.testing.assert_array_equal(np.asarray(si), np.asarray(ri))
    np.testing.assert_allclose(np.asarray(sd), np.asarray(rd),
                               rtol=1e-4, atol=1e-4)


def test_padding_rows_never_selected(mesh):
    # 9 rows over 8 devices pads to 16: 7 padding rows, and k=9 demands
    # every REAL row back
    rng = np.random.default_rng(4)
    Xt = rng.normal(size=(9, 6)).astype(np.float32)
    Xq = rng.normal(size=(3, 6)).astype(np.float32)
    idx, d2 = knn_indices_sharded(mesh, Xt, Xq, 9)
    assert np.asarray(idx).max() < 9
    assert np.all(np.asarray(d2) < 1e29)  # no _PAD_PENALTY leaked


def test_classifier_mesh_dispatch(mesh):
    rng = np.random.default_rng(5)
    X = np.concatenate([rng.normal(size=(60, 8)) + 4.0,
                        rng.normal(size=(60, 8)) - 4.0]).astype(np.float32)
    y = np.repeat([0, 1], 60)
    base = KNeighborsClassifier(n_neighbors=3).fit(X, y)
    meshed = KNeighborsClassifier(n_neighbors=3, mesh=mesh).fit(X, y)
    np.testing.assert_array_equal(meshed.predict(X), base.predict(X))
    np.testing.assert_allclose(meshed.predict_proba(X),
                               base.predict_proba(X), rtol=1e-5)
    d_m, i_m = meshed.kneighbors(X[:10])
    d_b, i_b = base.kneighbors(X[:10])
    np.testing.assert_array_equal(i_m, i_b)
    # self-queries have true distance 0; float32 GEMM round-off of ~1e-5
    # in d² becomes ~3e-3 after the sqrt, so the distance tolerance is
    # looser than the squared-distance comparisons elsewhere
    np.testing.assert_allclose(d_m, d_b, rtol=1e-4, atol=1e-2)


def test_classifier_mesh_warns_on_compute_dtype(mesh):
    rng = np.random.default_rng(6)
    X = rng.normal(size=(40, 8)).astype(np.float32)
    y = (rng.random(40) > 0.5).astype(int)
    knn = KNeighborsClassifier(n_neighbors=3, mesh=mesh,
                               compute_dtype="bfloat16").fit(X, y)
    with pytest.warns(RuntimeWarning, match="mesh path runs exact"):
        knn.predict(X[:5])


def test_corpus_placed_once_at_fit(mesh, monkeypatch):
    """Repeated meshed predicts must reuse the fit-time shard placement —
    re-shipping the corpus per predict is exactly the >=200 MB-upload
    relay hazard the cache exists to avoid."""
    rng = np.random.default_rng(7)
    X = rng.normal(size=(50, 8)).astype(np.float32)
    y = (rng.random(50) > 0.5).astype(int)
    knn = KNeighborsClassifier(n_neighbors=3, mesh=mesh).fit(X, y)
    from sq_learn_tpu.parallel import neighbors as pnbr

    def boom(*a, **k):
        raise AssertionError("corpus re-sharded after fit")

    monkeypatch.setattr(pnbr, "shard_train_rows", boom)
    knn.predict(X[:5])
    knn.kneighbors(X[:5])


@pytest.mark.slow
def test_sharded_knn_fuzz_matches_single_device(mesh):
    """Randomized (n, nq, m, k, n_devices) sweep crossing every padding
    and k/per-shard boundary: the sharded search must agree with the
    single-device kernel exactly on continuous data (no ties), on every
    mesh size from 1 to 8."""
    rng = np.random.default_rng(12)
    for _ in range(12):
        ndev = int(rng.choice([1, 2, 3, 5, 8]))
        sub = make_mesh(jax.devices("cpu")[:ndev])
        n = int(rng.integers(ndev, 400))
        nq = int(rng.integers(1, 60))
        m = int(rng.integers(1, 40))
        k = int(rng.integers(1, n + 1))
        Xt = rng.normal(size=(n, m)).astype(np.float32)
        Xq = rng.normal(size=(nq, m)).astype(np.float32)
        si, sd = knn_indices_sharded(sub, Xt, Xq, k)
        ri, rd = knn_indices(Xt, Xq, k)
        np.testing.assert_array_equal(
            np.asarray(si), np.asarray(ri),
            err_msg=f"ndev={ndev} n={n} nq={nq} m={m} k={k}")
        np.testing.assert_allclose(np.asarray(sd), np.asarray(rd),
                                   rtol=1e-4, atol=1e-4)
