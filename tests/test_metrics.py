"""Metrics parity vs sklearn."""

import jax.numpy as jnp
import numpy as np
import pytest
import sklearn.metrics
import sklearn.metrics.pairwise

from sq_learn_tpu.metrics import (
    accuracy_score,
    adjusted_rand_score,
    euclidean_distances,
    linear_kernel,
    pairwise_kernels,
    polynomial_kernel,
    rbf_kernel,
    sigmoid_kernel,
)


class TestScores:
    def test_ari_matches_sklearn(self):
        rng = np.random.RandomState(0)
        for _ in range(5):
            a = rng.randint(0, 5, 100)
            b = rng.randint(0, 4, 100)
            np.testing.assert_allclose(
                float(adjusted_rand_score(a, b)),
                sklearn.metrics.adjusted_rand_score(a, b),
                atol=1e-5,
            )

    def test_ari_perfect_and_permuted(self):
        labels = np.array([0, 0, 1, 1, 2, 2])
        assert float(adjusted_rand_score(labels, labels)) == pytest.approx(1.0)
        permuted = np.array([2, 2, 0, 0, 1, 1])
        assert float(adjusted_rand_score(labels, permuted)) == pytest.approx(1.0)

    def test_accuracy(self):
        assert float(accuracy_score([1, 1, -1], [1, -1, -1])) == pytest.approx(2 / 3)


class TestKernels:
    @pytest.fixture
    def data(self):
        rng = np.random.RandomState(1)
        return rng.randn(20, 6).astype(np.float32), rng.randn(8, 6).astype(np.float32)

    def test_linear(self, data):
        X, Y = data
        np.testing.assert_allclose(
            np.asarray(linear_kernel(X, Y)),
            sklearn.metrics.pairwise.linear_kernel(X, Y),
            rtol=1e-4,
        )

    def test_rbf(self, data):
        X, Y = data
        np.testing.assert_allclose(
            np.asarray(rbf_kernel(X, Y, gamma=0.3)),
            sklearn.metrics.pairwise.rbf_kernel(X, Y, gamma=0.3),
            rtol=1e-3,
        )

    def test_poly(self, data):
        X, Y = data
        np.testing.assert_allclose(
            np.asarray(polynomial_kernel(X, Y, degree=2, gamma=0.1, coef0=1.5)),
            sklearn.metrics.pairwise.polynomial_kernel(X, Y, degree=2, gamma=0.1, coef0=1.5),
            rtol=1e-3,
        )

    def test_sigmoid(self, data):
        X, Y = data
        np.testing.assert_allclose(
            np.asarray(sigmoid_kernel(X, Y, gamma=0.05, coef0=0.2)),
            sklearn.metrics.pairwise.sigmoid_kernel(X, Y, gamma=0.05, coef0=0.2),
            rtol=1e-3,
            atol=1e-5,
        )

    def test_euclidean(self, data):
        X, Y = data
        np.testing.assert_allclose(
            np.asarray(euclidean_distances(X, Y)),
            sklearn.metrics.pairwise.euclidean_distances(X, Y),
            rtol=1e-3,
            atol=1e-3,
        )

    def test_dispatch_unknown(self, data):
        with pytest.raises(ValueError, match="unknown kernel"):
            pairwise_kernels(data[0], metric="nope")
