"""Metrics parity vs sklearn."""

import jax.numpy as jnp
import numpy as np
import pytest
import sklearn.metrics
import sklearn.metrics.pairwise

from sq_learn_tpu.metrics import (
    accuracy_score,
    adjusted_rand_score,
    euclidean_distances,
    linear_kernel,
    pairwise_kernels,
    polynomial_kernel,
    rbf_kernel,
    sigmoid_kernel,
)


class TestScores:
    def test_ari_matches_sklearn(self):
        rng = np.random.RandomState(0)
        for _ in range(5):
            a = rng.randint(0, 5, 100)
            b = rng.randint(0, 4, 100)
            np.testing.assert_allclose(
                float(adjusted_rand_score(a, b)),
                sklearn.metrics.adjusted_rand_score(a, b),
                atol=1e-5,
            )

    def test_ari_perfect_and_permuted(self):
        labels = np.array([0, 0, 1, 1, 2, 2])
        assert float(adjusted_rand_score(labels, labels)) == pytest.approx(1.0)
        permuted = np.array([2, 2, 0, 0, 1, 1])
        assert float(adjusted_rand_score(labels, permuted)) == pytest.approx(1.0)

    def test_accuracy(self):
        assert float(accuracy_score([1, 1, -1], [1, -1, -1])) == pytest.approx(2 / 3)


class TestKernels:
    @pytest.fixture
    def data(self):
        rng = np.random.RandomState(1)
        return rng.randn(20, 6).astype(np.float32), rng.randn(8, 6).astype(np.float32)

    def test_linear(self, data):
        X, Y = data
        np.testing.assert_allclose(
            np.asarray(linear_kernel(X, Y)),
            sklearn.metrics.pairwise.linear_kernel(X, Y),
            rtol=1e-4,
        )

    def test_rbf(self, data):
        X, Y = data
        np.testing.assert_allclose(
            np.asarray(rbf_kernel(X, Y, gamma=0.3)),
            sklearn.metrics.pairwise.rbf_kernel(X, Y, gamma=0.3),
            rtol=1e-3,
        )

    def test_poly(self, data):
        X, Y = data
        np.testing.assert_allclose(
            np.asarray(polynomial_kernel(X, Y, degree=2, gamma=0.1, coef0=1.5)),
            sklearn.metrics.pairwise.polynomial_kernel(
                X, Y, degree=2, gamma=0.1, coef0=1.5),
            rtol=1e-3,
        )

    def test_sigmoid(self, data):
        X, Y = data
        np.testing.assert_allclose(
            np.asarray(sigmoid_kernel(X, Y, gamma=0.05, coef0=0.2)),
            sklearn.metrics.pairwise.sigmoid_kernel(X, Y, gamma=0.05, coef0=0.2),
            rtol=1e-3,
            atol=1e-5,
        )

    def test_euclidean(self, data):
        X, Y = data
        np.testing.assert_allclose(
            np.asarray(euclidean_distances(X, Y)),
            sklearn.metrics.pairwise.euclidean_distances(X, Y),
            rtol=1e-3,
            atol=1e-3,
        )

    def test_dispatch_unknown(self, data):
        with pytest.raises(ValueError, match="unknown kernel"):
            pairwise_kernels(data[0], metric="nope")


class TestExtendedScores:
    """NMI / confusion matrix / F1 / silhouette vs sklearn references."""

    def setup_method(self):
        rng = np.random.default_rng(0)
        self.yt = rng.integers(0, 4, 200)
        self.yp = np.where(rng.random(200) < 0.8, self.yt,
                           rng.integers(0, 4, 200))

    def test_nmi_matches_sklearn(self):
        from sklearn.metrics import normalized_mutual_info_score as sk_nmi

        from sq_learn_tpu.metrics import normalized_mutual_info_score

        ours = normalized_mutual_info_score(self.yt, self.yp)
        assert ours == pytest.approx(sk_nmi(self.yt, self.yp), abs=1e-6)
        assert normalized_mutual_info_score(self.yt, self.yt) == \
            pytest.approx(1.0)

    def test_confusion_matrix_matches_sklearn(self):
        from sklearn.metrics import confusion_matrix as sk_cm

        from sq_learn_tpu.metrics import confusion_matrix

        np.testing.assert_array_equal(confusion_matrix(self.yt, self.yp),
                                      sk_cm(self.yt, self.yp))

    @pytest.mark.parametrize("average", ["macro", "micro", "weighted"])
    def test_f1_matches_sklearn(self, average):
        from sklearn.metrics import f1_score as sk_f1

        from sq_learn_tpu.metrics import f1_score

        ours = f1_score(self.yt, self.yp, average=average)
        assert ours == pytest.approx(
            sk_f1(self.yt, self.yp, average=average), abs=1e-9)

    def test_f1_binary(self):
        from sklearn.metrics import f1_score as sk_f1

        from sq_learn_tpu.metrics import f1_score

        yt, yp = self.yt % 2, self.yp % 2
        assert f1_score(yt, yp) == pytest.approx(sk_f1(yt, yp), abs=1e-9)

    def test_silhouette_matches_sklearn(self):
        from sklearn.metrics import silhouette_score as sk_sil

        from sq_learn_tpu.datasets import make_blobs
        from sq_learn_tpu.metrics import silhouette_score

        X, y = make_blobs(n_samples=200, centers=3, n_features=6,
                          cluster_std=1.0, random_state=4)
        ours = silhouette_score(X, y)
        assert ours == pytest.approx(sk_sil(X, y), abs=1e-4)

    def test_silhouette_validations(self):
        from sq_learn_tpu.metrics import silhouette_score

        X = np.random.default_rng(0).normal(size=(10, 3))
        with pytest.raises(ValueError, match="n_labels"):
            silhouette_score(X, np.zeros(10, dtype=int))


class TestScoreEdgeCases:
    def test_confusion_matrix_negative_labels(self):
        from sklearn.metrics import confusion_matrix as sk_cm

        from sq_learn_tpu.metrics import confusion_matrix

        yt = np.array([-1, 0, 1, -1])
        yp = np.array([0, 0, 1, -1])
        np.testing.assert_array_equal(confusion_matrix(yt, yp),
                                      sk_cm(yt, yp))
        assert confusion_matrix(yt, yp).sum() == 4

    def test_f1_binary_pos_label_semantics(self):
        from sklearn.metrics import f1_score as sk_f1

        from sq_learn_tpu.metrics import f1_score

        yt, yp = np.array([1, 1, 2]), np.array([1, 1, 1])
        assert f1_score(yt, yp) == pytest.approx(sk_f1(yt, yp))
        with pytest.raises(ValueError, match="pos_label"):
            f1_score(np.array([0, 2]), np.array([0, 2]))
