"""PR 6 fused fit pipeline: sharded/batched k-means++ init kernels,
validate-once array contract, host-prestats native route, and the
while-loop convergence semantics of the whole-fit jit."""

import warnings

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import sq_learn_tpu.base as base_mod
from sq_learn_tpu import obs
from sq_learn_tpu.models import KMeans, MiniBatchQKMeans, QKMeans, QPCA
from sq_learn_tpu.parallel.init import (NBLOCKS, kmeans_plusplus_batched,
                                        kmeans_plusplus_sharded,
                                        resolve_init_subsample)


@pytest.fixture(scope="module")
def blobs():
    from sklearn.datasets import make_blobs

    X, y = make_blobs(n_samples=517, centers=5, n_features=12,
                      cluster_std=1.5, random_state=3)
    return X.astype(np.float32), y


@pytest.fixture
def mesh8():
    devs = jax.devices("cpu")
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return Mesh(np.asarray(devs[:8]), ("data",))


class TestInitKernelParity:
    """The layout-invariance contract of parallel/init.py: a fixed PRNG
    key selects the same centers on 1 device and on an 8-device mesh."""

    def test_sharded_matches_single_device_bitwise(self, blobs, mesh8):
        X, _ = blobs
        key = jax.random.PRNGKey(11)
        c1, i1 = kmeans_plusplus_batched(key, X, n_clusters=6, n_restarts=1)
        c8, i8 = kmeans_plusplus_sharded(mesh8, key, X, n_clusters=6)
        np.testing.assert_array_equal(np.asarray(i1[0]), np.asarray(i8))
        np.testing.assert_array_equal(np.asarray(c1[0]), np.asarray(c8))

    def test_deterministic_under_fixed_key(self, blobs):
        X, _ = blobs
        key = jax.random.PRNGKey(5)
        _, i_a = kmeans_plusplus_batched(key, X, n_clusters=4, n_restarts=3)
        _, i_b = kmeans_plusplus_batched(key, X, n_clusters=4, n_restarts=3)
        np.testing.assert_array_equal(np.asarray(i_a), np.asarray(i_b))
        # restarts draw distinct streams
        assert len({tuple(r) for r in np.asarray(i_a).tolist()}) > 1

    def test_centers_are_data_rows_and_weighted(self, blobs):
        X, _ = blobs
        key = jax.random.PRNGKey(2)
        c, i = kmeans_plusplus_batched(key, X, n_clusters=5, n_restarts=2)
        i = np.asarray(i)
        np.testing.assert_array_equal(np.asarray(c), X[i])
        # zero-weight rows are never selected
        w = np.ones(len(X), np.float32)
        w[64:] = 0.0
        _, iw = kmeans_plusplus_batched(key, X, n_clusters=5, n_restarts=3,
                                        weights=w)
        assert np.asarray(iw).max() < 64

    def test_subsampled_init_quality_and_determinism(self, blobs):
        X, _ = blobs
        key = jax.random.PRNGKey(9)
        c_s, i_s = kmeans_plusplus_batched(key, X, n_clusters=5,
                                           n_restarts=2, subsample=128)
        c_s2, i_s2 = kmeans_plusplus_batched(key, X, n_clusters=5,
                                             n_restarts=2, subsample=128)
        np.testing.assert_array_equal(np.asarray(i_s), np.asarray(i_s2))
        # indices map back to ORIGINAL rows
        np.testing.assert_array_equal(np.asarray(c_s),
                                      X[np.asarray(i_s)])
        # quality: subsampled potential stays within 2x of the full-data
        # potential (D² init is robust to uniform row sketching)
        xsq = (X**2).sum(1)

        def pot(C):
            d2 = xsq[:, None] + (C**2).sum(1)[None, :] - 2.0 * X @ C.T
            return float(np.maximum(d2.min(1), 0).sum())

        c_f, _ = kmeans_plusplus_batched(key, X, n_clusters=5, n_restarts=2)
        full = min(pot(np.asarray(c_f[r])) for r in range(2))
        sub = min(pot(np.asarray(c_s[r])) for r in range(2))
        assert sub <= 2.0 * full

    def test_resolve_policy(self):
        # 'auto' engages only when the data dwarfs the target
        assert resolve_init_subsample(70_000, 10) == 4096
        assert resolve_init_subsample(1_000, 10) == 0
        assert resolve_init_subsample(70_000, 10, 0) == 0
        assert resolve_init_subsample(70_000, 10, None) == 0
        # explicit targets round up to the block grid
        assert resolve_init_subsample(10**6, 10, 1000) % NBLOCKS == 0

    def test_mesh_estimator_uses_sharded_init(self, blobs, mesh8,
                                              monkeypatch):
        X, y = blobs
        import sq_learn_tpu.parallel.init as pinit

        calls = []
        real = pinit.kmeans_plusplus_sharded

        def spy(*a, **kw):
            calls.append(1)
            return real(*a, **kw)

        monkeypatch.setattr(pinit, "kmeans_plusplus_sharded", spy)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            QKMeans(n_clusters=5, n_init=1, random_state=0,
                    mesh=mesh8).fit(X)
        assert calls, "mesh fit did not route init through the sharded kernel"


class TestFusedClassicalParity:
    """δ=0 must short-circuit to the exact classical computation."""

    def _fused(self, X, **kw):
        est = QKMeans(**kw)
        delta = 0.0 if est.delta is None else float(est.delta)
        w = np.ones(len(X), np.float32)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            out = est._fit_fused(X, w, delta, est._mode(delta))
        assert out is est
        return est

    def test_delta0_fused_bit_equal_to_classical_kernels(self, blobs):
        """The two-dispatch fused δ=0 fit reproduces the staged classical
        XLA kernels (same key discipline) bit for bit."""
        from sq_learn_tpu.models.qkmeans import fit_prestats
        from sq_learn_tpu.utils import as_key

        X, _ = blobs
        est = self._fused(X, n_clusters=4, n_init=3, delta=0.0,
                          random_state=7)
        key = as_key(7)
        # staged twin: same key split as _fit_fused
        k_init, k_run = jax.random.split(key)
        stats = fit_prestats(jnp.asarray(X), quantum=False)
        w = jnp.ones(len(X), jnp.float32)
        from sq_learn_tpu.models.qkmeans import (_restart_inits,
                                                 lloyd_restarts_from)

        centers0 = _restart_inits(k_init, stats["Xc"], w, stats["xsq"],
                                  n_init=3, init="k-means++", n_clusters=4)
        # fused_fit computes tol in f32 on device; mirror that exactly
        tol = float(jnp.asarray(1e-4, jnp.float32) * stats["var_mean"])
        labels, inertia, centers, n_iter, _ = lloyd_restarts_from(
            k_run, stats["Xc"], w, stats["xsq"], centers0, tol=tol)
        np.testing.assert_array_equal(est.labels_, np.asarray(labels))
        np.testing.assert_allclose(
            est.cluster_centers_,
            np.asarray(centers) + np.asarray(stats["mean"]), rtol=1e-6)
        np.testing.assert_allclose(est.inertia_, float(inertia), rtol=1e-6)
        assert est.n_iter_ == int(n_iter)

    def test_delta0_draws_nothing(self, blobs):
        """With δ=0 the error model is OFF: different random_state with the
        same deterministic init stack must give bit-identical fits (the
        zero-error-budget short-circuit contract)."""
        X, _ = blobs
        # deterministic init: disable the k-means++ stream by fixing the
        # restart count to 1 and comparing two seeds' Lloyd runs from the
        # SAME centers via the functional kernel
        from sq_learn_tpu.models.qkmeans import lloyd_single_jit

        Xd = jnp.asarray(X - X.mean(0))
        xsq = jnp.sum(Xd * Xd, axis=1)
        w = jnp.ones(len(X), jnp.float32)
        c0 = Xd[:4]
        outs = []
        for seed in (0, 123):
            labels, inertia, centers, n_iter, _ = lloyd_single_jit(
                jax.random.PRNGKey(seed), Xd, w, c0, xsq, delta=0.0,
                mode="classic", tol=1e-5)
            outs.append((np.asarray(labels), float(inertia),
                         np.asarray(centers), int(n_iter)))
        np.testing.assert_array_equal(outs[0][0], outs[1][0])
        assert outs[0][1] == outs[1][1]
        np.testing.assert_array_equal(outs[0][2], outs[1][2])
        assert outs[0][3] == outs[1][3]

    def test_classical_kmeans_facade_matches_delta0(self, blobs):
        X, _ = blobs
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            a = QKMeans(n_clusters=4, n_init=2, delta=0.0,
                        random_state=0).fit(X)
            b = KMeans(n_clusters=4, n_init=2, random_state=0).fit(X)
        np.testing.assert_array_equal(a.labels_, b.labels_)
        np.testing.assert_array_equal(a.cluster_centers_, b.cluster_centers_)


class TestWhileLoopSemantics:
    """The lax.while_loop convergence carry (tolerance + patience) matches
    the documented Python-stepped stopping rules exactly."""

    def _python_n_iter(self, inertia_tr, shift_tr, tol, patience, max_iter):
        best, best_it = np.inf, 0
        it = 0
        while it < max_iter and not np.isnan(shift_tr[it]):
            if inertia_tr[it] < best:
                best, best_it = inertia_tr[it], it
            it += 1
            if shift_tr[it - 1] <= tol:
                break
            if patience is not None and it - best_it > patience:
                break
        return it

    @pytest.mark.parametrize("delta,mode,patience", [
        (0.0, "classic", None),
        (0.6, "delta", 3),
        (0.6, "delta", 0),
    ])
    def test_n_iter_matches_trace_replay(self, blobs, delta, mode,
                                         patience):
        from sq_learn_tpu.models.qkmeans import lloyd_single_jit

        X, _ = blobs
        Xd = jnp.asarray(X - X.mean(0))
        xsq = jnp.sum(Xd * Xd, axis=1)
        w = jnp.ones(len(X), jnp.float32)
        c0 = Xd[7:12]
        tol = 1e-3
        labels, inertia, centers, n_iter, hist = lloyd_single_jit(
            jax.random.PRNGKey(0), Xd, w, c0, xsq, delta=delta, mode=mode,
            max_iter=40, tol=tol, patience=patience)
        replay = self._python_n_iter(
            np.asarray(hist["inertia"]), np.asarray(hist["center_shift"]),
            tol, patience, 40)
        assert int(n_iter) == replay
        # traces are NaN beyond n_iter and finite before it
        assert np.all(np.isfinite(np.asarray(hist["inertia"])[:int(n_iter)]))
        assert np.all(np.isnan(np.asarray(hist["inertia"])[int(n_iter):]))

    def test_host_runner_same_rules(self, blobs):
        """The native host loop stops by the same (shift<=tol, patience)
        rules — replaying its own traces reproduces its n_iter."""
        from sq_learn_tpu import native
        from sq_learn_tpu.models.qkmeans import _native_lloyd_run

        X, _ = blobs
        Xn = np.ascontiguousarray(X - X.mean(0), np.float32)
        wn = np.ones(len(Xn), np.float32)
        xsq = (Xn**2).sum(1)
        rng = np.random.default_rng(0)
        labels, inertia, centers, n_iter, hist = _native_lloyd_run(
            rng, Xn, wn, xsq, Xn[7:12].copy(), window=0.4, max_iter=40,
            tol=1e-3, patience=3, use_cpp=native.native_available())
        replay = self._python_n_iter(hist["inertia"], hist["center_shift"],
                                     1e-3, 3, 40)
        assert int(n_iter) == replay


class TestFusedFitObs:
    def test_fused_fit_compile_budget(self, blobs, tmp_path):
        """Two same-shape fused fits mint at most one compile per kernel
        signature — the watchdog budget the fused path declares."""
        X, _ = blobs
        obs.enable(path=str(tmp_path / "obs.jsonl"))
        try:
            for seed in (0, 1):
                est = QKMeans(n_clusters=4, n_init=2, random_state=seed)
                w = np.ones(len(X), np.float32)
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    assert est._fit_fused(X, w, 0.0, "classic") is est
            report = obs.watchdog.report()
            for site in ("qkmeans.fused_init", "qkmeans.fused_fit"):
                assert site in report
                assert not report[site]["over_budget"], report[site]
                assert report[site]["compiles"] <= report[site]["budget"]
        finally:
            obs.disable()

    def test_native_fit_spans_and_provenance(self, blobs, tmp_path):
        X, _ = blobs
        path = tmp_path / "obs.jsonl"
        obs.enable(path=str(path))
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                est = QKMeans(n_clusters=4, n_init=2, delta=0.5,
                              true_distance_estimate=False,
                              random_state=0).fit(X)
        finally:
            obs.disable()
        assert est.ingest_ == "host"
        import json

        spans = [json.loads(l)["name"] for l in open(path)
                 if '"type": "span"' in l]
        for name in ("qkmeans.prestats", "qkmeans.native_init",
                     "qkmeans.native_lloyd", "qkmeans.quantum_stats",
                     "qkmeans.fit"):
            assert name in spans, (name, spans)
        # quantum stats exist and are real numbers
        assert est.eta_ > 0 and np.isfinite(est.mu_)


class TestHostPrestatsRoute:
    def test_matches_staged_device_path(self, blobs):
        """The host-prestats native fit agrees with the staged XLA path on
        statistics and quality (engines differ, distributions match)."""
        X, _ = blobs
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            host = QKMeans(n_clusters=5, n_init=2, delta=0.5,
                           true_distance_estimate=False,
                           random_state=0).fit(X)
            # forcing a non-auto kernel disables the native route: the
            # staged XLA path with streamed/monolithic ingest runs instead
            staged = QKMeans(n_clusters=5, n_init=2, delta=0.5,
                             true_distance_estimate=False,
                             use_pallas=False, random_state=0).fit(X)
        assert host.ingest_ == "host"
        assert staged.ingest_ in ("monolithic", "streamed")
        # deterministic quantum statistics agree across engines
        np.testing.assert_allclose(host.eta_, staged.eta_, rtol=1e-5)
        np.testing.assert_allclose(host.mu_, staged.mu_, rtol=1e-4)
        np.testing.assert_allclose(host.condition_number_,
                                   staged.condition_number_, rtol=1e-2)
        from sklearn.metrics import adjusted_rand_score

        assert adjusted_rand_score(host.labels_, staged.labels_) > 0.9

    def test_explicit_init_array_host_route(self, blobs):
        X, _ = blobs
        init = X[3:8].copy()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            est = QKMeans(n_clusters=5, init=init, n_init=1,
                          random_state=0).fit(X)
        assert est.ingest_ == "host"
        assert est.cluster_centers_.shape == (5, X.shape[1])


class TestValidateOnce:
    @pytest.fixture
    def spy(self, monkeypatch):
        from sq_learn_tpu.utils.validation import check_array as real

        counts = {"n": 0}

        def counting(X, **kw):
            counts["n"] += 1
            return real(X, **kw)

        monkeypatch.setattr(base_mod, "check_array", counting,
                            raising=False)
        # base._validated_X imports at call time from utils.validation
        import sq_learn_tpu.utils.validation as val

        monkeypatch.setattr(val, "check_array", counting)
        return counts

    def test_qkmeans_fit_transform_validates_once(self, blobs, spy):
        X, _ = blobs
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            QKMeans(n_clusters=4, n_init=1, random_state=0).fit_transform(X)
        assert spy["n"] == 1, spy

    def test_qkmeans_fit_predict_then_transform_outside_scope(self, blobs,
                                                              spy):
        # outside fit_transform, each public call re-validates (nothing is
        # trusted across estimator calls)
        X, _ = blobs
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            est = QKMeans(n_clusters=4, n_init=1, random_state=0).fit(X)
            est.transform(X)
        assert spy["n"] == 2, spy

    def test_qpca_fit_transform_validates_once(self, blobs, spy):
        X, _ = blobs
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            QPCA(n_components=3, random_state=0).fit_transform(X)
        assert spy["n"] == 1, spy

    def test_minibatch_fit_transform_validates_once(self, blobs, spy):
        X, _ = blobs
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            MiniBatchQKMeans(n_clusters=4, n_init=1, max_iter=2,
                             batch_size=128,
                             random_state=0).fit_transform(X)
        assert spy["n"] == 1, spy

    def test_tiny_routed_transform_validates_once(self, blobs, spy,
                                                  monkeypatch):
        """The tiny-route re-entry (transform under the cpu pin) must not
        re-validate — the latent double-validation this PR fixes."""
        import sq_learn_tpu._config as cfg

        X, _ = blobs
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            est = QKMeans(n_clusters=4, n_init=1, random_state=0).fit(X)
        spy["n"] = 0
        # simulate the accelerator-backend tiny route: the first backend
        # check says "accelerator", the re-entry (under the cpu pin) says
        # cpu — exactly the production re-entry shape
        seq = {"n": 0}

        def fake_cpu():
            seq["n"] += 1
            return seq["n"] > 1

        monkeypatch.setattr(cfg, "on_cpu_backend", fake_cpu)
        monkeypatch.setattr(cfg, "route_tiny_fit_to_host", lambda n: True)
        est.transform(X)
        assert spy["n"] == 1, spy

    def test_mutated_input_revalidated_after_scope(self, blobs):
        """The cache dies with the scope: a NaN injected after
        fit_transform is caught by the next call."""
        X, _ = blobs
        X = X.copy()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            est = QKMeans(n_clusters=4, n_init=1, random_state=0)
            est.fit_transform(X)
        X[0, 0] = np.nan
        with pytest.raises(ValueError, match="NaN"):
            est.transform(X)


class TestStreamedKppInit:
    def test_transfers_capped_and_compile_bucketed(self, monkeypatch,
                                                   tmp_path):
        from sq_learn_tpu import streaming

        rng = np.random.default_rng(0)
        X = rng.normal(size=(1003, 24)).astype(np.float32)
        tile_bytes = 150 * 24 * 4
        monkeypatch.setenv("SQ_STREAM_TILE_BYTES", str(tile_bytes))
        sizes = []
        real_put = jax.device_put

        def recording(x, *a, **kw):
            sizes.append(int(getattr(x, "nbytes", 0)))
            return real_put(x, *a, **kw)

        monkeypatch.setattr(jax, "device_put", recording)
        obs.enable(path=str(tmp_path / "obs.jsonl"))
        try:
            C, idx = streaming.streamed_kmeans_plusplus(
                jax.random.PRNGKey(3), X, 5)
            report = obs.watchdog.report()
        finally:
            obs.disable()
        assert C.shape == (5, 24)
        np.testing.assert_array_equal(C, X[idx])
        assert max(sizes) <= tile_bytes
        wd = report.get("streaming.kpp_score")
        assert wd is not None and not wd["over_budget"], wd

    def test_zero_weight_rows_never_selected(self):
        from sq_learn_tpu.streaming import streamed_kmeans_plusplus

        rng = np.random.default_rng(1)
        X = rng.normal(size=(400, 16)).astype(np.float32)
        w = np.zeros(400, np.float32)
        w[:37] = 1.0
        _, idx = streamed_kmeans_plusplus(jax.random.PRNGKey(0), X, 6,
                                          weights=w)
        assert idx.max() < 37


class TestMiniBatchHostStep:
    def test_partial_fit_host_matches_device_step(self):
        """partial_fit's host fast path (CPU backend) agrees with the
        device kernel's Sculley update on the classical mode."""
        rng = np.random.default_rng(0)
        X0 = rng.normal(size=(256, 8)).astype(np.float32)
        Xb = rng.normal(size=(128, 8)).astype(np.float32)

        host = MiniBatchQKMeans(n_clusters=4, random_state=0,
                                reassignment_ratio=0.0)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            host.partial_fit(X0)   # first call inits on the device kernel
            host.partial_fit(Xb)   # second call takes the host fast path
        assert host.fit_backend_ == "cpu"
        # device twin of the second step, from the same post-init state
        est_d = MiniBatchQKMeans(n_clusters=4, random_state=0,
                                 reassignment_ratio=0.0)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            est_d.partial_fit(X0)
        from sq_learn_tpu.models.minibatch import minibatch_step_jit

        centers, counts, _ = minibatch_step_jit(
            jax.random.PRNGKey(0), jnp.asarray(Xb),
            jnp.ones(len(Xb), jnp.float32),
            jnp.asarray(est_d.cluster_centers_),
            jnp.asarray(est_d.counts_), jnp.asarray(1),
            delta=0.0, mode="classic", ipe_q=5, reassignment_ratio=0.0)
        np.testing.assert_allclose(host.cluster_centers_,
                                   np.asarray(centers), rtol=1e-4,
                                   atol=1e-4)
        np.testing.assert_allclose(host.counts_, np.asarray(counts),
                                   rtol=1e-5)
