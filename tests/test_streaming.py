"""Streaming tiled-ingestion engine tests.

Parity contract: streamed results must match the monolithic path in the
same dtype. Quantities whose computation is row-independent (resident
assembly, classic predict labels) are pinned exactly equal; tile-summed
reductions (Gram, column mean) reassociate float adds across tiles, so
they are pinned to tight tolerances instead — tolerance-free equality
there would pin XLA's reduction order, not our engine.

Transfer accounting monkeypatches ``jax.device_put`` (the engine resolves
it late, so the patch sees every tile) and asserts no single streamed
transfer exceeds the configured tile bytes.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from sq_learn_tpu import streaming
from sq_learn_tpu.models import QPCA, QKMeans, KNeighborsClassifier
from sq_learn_tpu.models.qkmeans import fit_prestats
from sq_learn_tpu.ops.linalg import centered_svd_topk, randomized_svd


RNG = np.random.default_rng(0)
# 1003 rows: a ragged final tile for every divisor-ish tile size below
X_TALL = (RNG.normal(size=(1003, 16)) + 2.0).astype(np.float32)
ROW_BYTES = X_TALL.nbytes // X_TALL.shape[0]
TILE_BYTES = 150 * ROW_BYTES  # ~7 tiles, tail of 103 → bucket 128


class TestTiler:
    def test_plan_row_tiles(self):
        rows, n_tiles = streaming.plan_row_tiles(1003, ROW_BYTES,
                                                 TILE_BYTES)
        assert rows == 150
        assert n_tiles == 7

    def test_bucket_rows_pow2_tail(self):
        assert streaming._bucket_rows(150, 150) == 150
        assert streaming._bucket_rows(103, 150) == 128
        assert streaming._bucket_rows(3, 150) == 64   # floor bucket
        assert streaming._bucket_rows(140, 150) == 150  # cap at full tile

    def test_bucket_rows_multiple(self):
        # mesh buckets round to device-count multiples
        assert streaming._bucket_rows(65, 150, multiple=8) == 128
        assert streaming._bucket_rows(3, 150, multiple=8) == 64

    def test_bucket_rows_per_call_min_rows(self):
        """The serving dispatcher's per-call floor: serving-sized
        buckets without mutating SQ_STREAM_MIN_BUCKET_ROWS — and the
        default path stays bit-identical to the env-derived floor."""
        assert streaming.bucket_rows(3, 512, min_rows=8) == 8
        assert streaming.bucket_rows(9, 512, min_rows=8) == 16
        assert streaming.bucket_rows(600, 512, min_rows=8) == 512
        assert streaming.bucket_rows(3, 512, min_rows=8, multiple=8) == 8
        # default min_rows: identical to the module-level floor
        assert (streaming.bucket_rows(3, 150)
                == streaming._bucket_rows(3, 150) == 64)

    def test_tiles_cover_rows_with_zero_padding(self):
        seen = np.zeros(1003, bool)
        for tile, n_valid, start in streaming.stream_tiles(
                X_TALL, max_bytes=TILE_BYTES):
            t = np.asarray(tile)
            assert np.array_equal(t[:n_valid],
                                  X_TALL[start:start + n_valid])
            assert not t[n_valid:].any()  # zero padding
            seen[start:start + n_valid] = True
        assert seen.all()


class TestTransferAccounting:
    """No single device_put in a streamed fit exceeds the tile bytes."""

    @pytest.fixture
    def recorded_puts(self, monkeypatch):
        sizes = []
        real_put = jax.device_put

        def recording(x, *a, **kw):
            sizes.append(int(getattr(x, "nbytes", 0)))
            return real_put(x, *a, **kw)

        monkeypatch.setattr(jax, "device_put", recording)
        return sizes

    def test_streamed_qpca_fit_transfers_capped(self, monkeypatch,
                                                recorded_puts):
        monkeypatch.setenv("SQ_STREAM_TILE_BYTES", str(TILE_BYTES))
        pca = QPCA(n_components=3, svd_solver="full",
                   ingest="streamed").fit(X_TALL)
        assert pca.ingest_ == "streamed"
        assert recorded_puts, "no transfer was recorded"
        assert max(recorded_puts) <= TILE_BYTES

    def test_streamed_qkmeans_fit_transfers_capped(self, monkeypatch,
                                                   recorded_puts):
        monkeypatch.setenv("SQ_STREAM_TILE_BYTES", str(TILE_BYTES))
        # a forced (non-'auto') kernel keeps the staged XLA path — the
        # default CPU fit now runs host-native end to end (see below)
        km = QKMeans(n_clusters=3, n_init=1, random_state=0,
                     use_pallas=False).fit(X_TALL)
        assert km.ingest_ == "streamed"
        # the tile uploads are the big transfers; centers/keys are tiny
        big = [s for s in recorded_puts if s > 64 * ROW_BYTES]
        assert big, "no tile-sized transfer was recorded"
        assert max(recorded_puts) <= TILE_BYTES

    def test_default_cpu_qkmeans_fit_never_uploads(self, monkeypatch,
                                                   recorded_puts):
        """The PR 6 host route: a default classical CPU-backend fit does
        the whole pipeline in host memory — zero device_put of the data
        (the streamed ingest + fetch-back it replaced was ~40 % of
        non-Lloyd fit time at MNIST scale)."""
        monkeypatch.setenv("SQ_STREAM_TILE_BYTES", str(TILE_BYTES))
        km = QKMeans(n_clusters=3, n_init=1, random_state=0).fit(X_TALL)
        assert km.ingest_ == "host"
        big = [s for s in recorded_puts if s > 64 * ROW_BYTES]
        assert not big, f"host-routed fit uploaded tiles: {big}"


class TestGramParity:
    """Streamed Gram/partial-U route vs the monolithic kernel, including a
    ragged final tile (1003 % 150 = 103) and a bucket-boundary row count
    (an exact multiple: no tail tile at all)."""

    @pytest.mark.parametrize("n_rows", [1003, 900])  # ragged, exact tiles
    def test_streamed_centered_svd_topk(self, n_rows):
        X = X_TALL[:n_rows]
        mean_s, Uk_s, S_s, Vt_s = streaming.streamed_centered_svd_topk(
            X, 3, max_bytes=TILE_BYTES)
        mean_m, Uk_m, S_m, Vt_m = centered_svd_topk(jnp.asarray(X), 3)
        assert np.asarray(S_s).dtype == np.asarray(S_m).dtype
        assert np.asarray(Uk_s).shape == np.asarray(Uk_m).shape
        np.testing.assert_allclose(np.asarray(mean_s), np.asarray(mean_m),
                                   rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(np.asarray(S_s), np.asarray(S_m),
                                   rtol=1e-4, atol=1e-3)
        np.testing.assert_allclose(np.asarray(Uk_s), np.asarray(Uk_m),
                                   rtol=1e-2, atol=1e-3)
        np.testing.assert_allclose(np.asarray(Vt_s[:3]),
                                   np.asarray(Vt_m[:3]),
                                   rtol=1e-2, atol=1e-3)

    def test_streamed_gram_matches_direct(self):
        mean, Gc, n = streaming.streamed_centered_gram(
            X_TALL, max_bytes=TILE_BYTES)
        Xc = X_TALL - X_TALL.mean(0, dtype=np.float64).astype(np.float32)
        ref = Xc.T.astype(np.float64) @ Xc.astype(np.float64)
        assert n == 1003
        scale = np.abs(ref).max()
        assert np.abs(np.asarray(Gc, np.float64) - ref).max() < 1e-5 * scale

    def test_single_tile_degenerates_to_monolithic_math(self):
        # max_bytes larger than X: one tile, no padding
        mean, Gc, _ = streaming.streamed_centered_gram(
            X_TALL, max_bytes=X_TALL.nbytes * 2)
        Xc = X_TALL - np.asarray(mean)
        np.testing.assert_allclose(np.asarray(Gc), Xc.T @ Xc,
                                   rtol=1e-4, atol=1e-2)


class TestRangeFinderParity:
    @pytest.mark.parametrize("n_rows", [1003, 900])
    def test_streamed_randomized_svd(self, key, n_rows):
        X = X_TALL[:n_rows]
        U_s, S_s, Vt_s = streaming.streamed_randomized_svd(
            key, X, 4, max_bytes=TILE_BYTES)
        U_m, S_m, Vt_m = randomized_svd(key, jnp.asarray(X), 4)
        assert np.asarray(S_s).dtype == np.asarray(S_m).dtype
        np.testing.assert_allclose(np.asarray(S_s), np.asarray(S_m),
                                   rtol=1e-3)
        # same key, same subspace: the leading components align to sign
        dots = np.abs(np.sum(np.asarray(Vt_s) * np.asarray(Vt_m), axis=1))
        np.testing.assert_allclose(dots, 1.0, atol=1e-3)

    def test_truncated_svd_streamed_estimator(self):
        from sq_learn_tpu.models import TruncatedSVD

        m = TruncatedSVD(n_components=3, random_state=0,
                         ingest="monolithic").fit(X_TALL)
        import os

        os.environ["SQ_STREAM_TILE_BYTES"] = str(TILE_BYTES)
        try:
            s = TruncatedSVD(n_components=3, random_state=0,
                             ingest="streamed").fit(X_TALL)
        finally:
            del os.environ["SQ_STREAM_TILE_BYTES"]
        assert s.ingest_ == "streamed" and m.ingest_ == "monolithic"
        np.testing.assert_allclose(s.singular_values_, m.singular_values_,
                                   rtol=1e-3)
        dots = np.abs(np.sum(s.components_ * m.components_, axis=1))
        np.testing.assert_allclose(dots, 1.0, atol=1e-3)


class TestPrestatsParity:
    @pytest.mark.parametrize("n_rows", [1003, 900])
    def test_streamed_prestats(self, n_rows):
        X = X_TALL[:n_rows]
        stats = streaming.streamed_prestats(X, max_bytes=TILE_BYTES)
        ref = fit_prestats(jnp.asarray(X))
        # the resident assembly is byte-identical by construction; the
        # centered matrix inherits only the tile-summed mean's ulp noise
        for name, tol in (("mean", 1e-6), ("Xc", 1e-5), ("xsq", 1e-3),
                          ("var_mean", 1e-5)):
            a, b = np.asarray(stats[name]), np.asarray(ref[name])
            assert a.dtype == b.dtype and a.shape == b.shape
            np.testing.assert_allclose(a, b, rtol=tol, atol=tol,
                                       err_msg=name)

    def test_streamed_prestats_quantum(self):
        from sq_learn_tpu.models.qkmeans import MU_GRID

        stats = streaming.streamed_prestats(
            X_TALL, quantum=True, mu_grid=MU_GRID, max_bytes=TILE_BYTES)
        ref = fit_prestats(jnp.asarray(X_TALL), quantum=True,
                           mu_grid=MU_GRID)
        # quantum stats are computed on the resident assembled buffer —
        # the same values the monolithic kernel sees, so exact equality
        for name in ("eta", "frob", "sigma_min", "mu_vals"):
            a, b = np.asarray(stats[name]), np.asarray(ref[name])
            assert a.dtype == b.dtype
            np.testing.assert_array_equal(a, b, err_msg=name)

    def test_streamed_qkmeans_fit_matches_monolithic(self, monkeypatch):
        # use_pallas=False keeps the staged XLA path (the default CPU fit
        # is host-native since PR 6 and never ingests onto the device)
        init = X_TALL[:3].copy()
        km_m = QKMeans(n_clusters=3, init=init, n_init=1,
                       use_pallas=False, random_state=0).fit(X_TALL)
        monkeypatch.setenv("SQ_STREAM_TILE_BYTES", str(TILE_BYTES))
        km_s = QKMeans(n_clusters=3, init=init, n_init=1,
                       use_pallas=False, random_state=0).fit(X_TALL)
        assert km_s.ingest_ == "streamed" and km_m.ingest_ == "monolithic"
        np.testing.assert_allclose(km_s.cluster_centers_,
                                   km_m.cluster_centers_,
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(km_s.inertia_, km_m.inertia_,
                                   rtol=1e-5)


class TestStreamedPredict:
    def test_qkmeans_streamed_predict_exact(self, monkeypatch):
        km = QKMeans(n_clusters=3, init=X_TALL[:3].copy(), n_init=1,
                     random_state=0).fit(X_TALL)
        ref = km.predict(X_TALL)
        # compute_dtype='float32' (a no-op precision-wise) skips the host
        # fast path so the device branch — where streaming engages — runs
        km.compute_dtype = "float32"
        ref_dev = km.predict(X_TALL)
        monkeypatch.setenv("SQ_STREAM_TILE_BYTES", str(TILE_BYTES))
        streamed = km.predict(X_TALL)
        # classic-mode labels are row-independent: exact equality
        np.testing.assert_array_equal(streamed, ref_dev)
        np.testing.assert_array_equal(streamed, ref)

    def test_knn_streamed_predict_exact(self, monkeypatch):
        y = (np.arange(len(X_TALL)) % 3)
        kn = KNeighborsClassifier(n_neighbors=3,
                                  compute_dtype="float32").fit(X_TALL, y)
        d_ref, i_ref = kn.kneighbors(X_TALL[:257])
        monkeypatch.setenv("SQ_STREAM_TILE_BYTES", str(64 * ROW_BYTES))
        d_s, i_s = kn.kneighbors(X_TALL[:257])
        np.testing.assert_array_equal(i_s, i_ref)
        np.testing.assert_allclose(d_s, d_ref, rtol=1e-5, atol=1e-5)


class TestCompileDiscipline:
    def test_no_per_shape_recompile_across_row_count_sweep(self):
        """5 row counts through the Gram pass: compile-cache entries stay
        pinned to the distinct (bucket, dtype) signatures (≤ 2 per
        bucket), never one per row count."""
        sweep = [551, 667, 782, 900, 1003]
        buckets = set()
        for size in sweep:
            rows, _ = streaming.plan_row_tiles(size, ROW_BYTES, TILE_BYTES)
            buckets.add(rows)
            tail = size % rows
            if tail:
                buckets.add(streaming._bucket_rows(tail, rows))
        before = streaming.kernel_cache_sizes()["gram_colsum"]
        for size in sweep:
            streaming.streamed_centered_gram(X_TALL[:size],
                                             max_bytes=TILE_BYTES)
        after = streaming.kernel_cache_sizes()["gram_colsum"]
        assert after <= 2 * len(buckets)
        # and the sweep itself minted at most the new buckets, not one
        # compile per row count
        assert after - before <= len(buckets)


class TestMeshStreaming:
    def test_streamed_gram_sharded_parity(self, mesh8):
        from sq_learn_tpu.parallel.streaming import \
            streamed_centered_gram_sharded

        mean, Gc, n = streamed_centered_gram_sharded(
            mesh8, X_TALL, max_bytes=TILE_BYTES)
        mean_1, Gc_1, _ = streaming.streamed_centered_gram(
            X_TALL, max_bytes=TILE_BYTES)
        assert n == 1003
        np.testing.assert_allclose(np.asarray(mean), np.asarray(mean_1),
                                   rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(np.asarray(Gc), np.asarray(Gc_1),
                                   rtol=1e-4, atol=1e-2)

    def test_streamed_topk_sharded_vs_resident_mesh_svd(self, mesh8):
        from sq_learn_tpu.parallel.pca import centered_svd_sharded
        from sq_learn_tpu.parallel.streaming import \
            streamed_centered_svd_topk_sharded

        mean_s, Uk, S_s, Vt_s = streamed_centered_svd_topk_sharded(
            mesh8, X_TALL, 3, max_bytes=TILE_BYTES)
        mean_m, U_m, S_m, Vt_m = centered_svd_sharded(mesh8, X_TALL)
        assert Uk.shape == (1003, 3)
        np.testing.assert_allclose(np.asarray(S_s)[:16], np.asarray(S_m),
                                   rtol=1e-4, atol=1e-3)
        np.testing.assert_allclose(Uk, np.asarray(U_m)[:, :3],
                                   rtol=1e-2, atol=1e-3)

    def test_qpca_mesh_streamed_fit(self, mesh8, monkeypatch):
        ref = QPCA(n_components=3, svd_solver="full", mesh=mesh8,
                   ingest="monolithic").fit(X_TALL)
        monkeypatch.setenv("SQ_STREAM_TILE_BYTES", str(TILE_BYTES))
        got = QPCA(n_components=3, svd_solver="full", mesh=mesh8).fit(
            X_TALL)
        assert got.ingest_ == "streamed"
        np.testing.assert_allclose(got.explained_variance_ratio_,
                                   ref.explained_variance_ratio_,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(got.components_, ref.components_,
                                   rtol=1e-2, atol=1e-3)
        np.testing.assert_allclose(got.left_sv, ref.left_sv,
                                   rtol=1e-2, atol=1e-3)


class TestIngestResolution:
    def test_qadra_fit_vetoes_streaming_with_warning(self):
        with pytest.warns(RuntimeWarning, match="ingest='streamed'"):
            pca = QPCA(n_components=3, svd_solver="full",
                       ingest="streamed").fit(
                X_TALL, estimate_all=True, eps=0.1, delta=0.1,
                theta_major=1e-9, true_tomography=False)
        assert pca.ingest_ == "monolithic"
        assert np.isfinite(pca.estimate_s_values).all()

    def test_auto_respects_tile_cap(self, monkeypatch):
        # input below the cap: no streaming
        pca = QPCA(n_components=3, svd_solver="full").fit(X_TALL)
        assert pca.ingest_ == "monolithic"
        monkeypatch.setenv("SQ_STREAM_TILE_BYTES", str(TILE_BYTES))
        pca = QPCA(n_components=3, svd_solver="full").fit(X_TALL)
        assert pca.ingest_ == "streamed"

    def test_invalid_ingest_rejected(self):
        with pytest.raises(ValueError, match="ingest"):
            QPCA(n_components=3, svd_solver="full", ingest="nope").fit(
                X_TALL)
