"""Headline benchmark (BASELINE.md config #1): q-means on digits 1797x64 k=10.

Compares our TPU q-means (delta-means quantum mode) fit wall-clock against
classical scikit-learn KMeans on the same data/settings, and checks ARI
agreement. Prints ONE JSON line:
    {"metric": ..., "value": seconds, "unit": "s", "vs_baseline": ratio}
vs_baseline = sklearn_seconds / our_seconds (>1 means we are faster).
"""

import json
import os
import sys
import time
import warnings

import numpy as np

warnings.filterwarnings("ignore")


# shared with the bench/ suite scripts — single implementation of the
# probe-in-subprocess + CPU fallback contract
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from bench._common import probe_backend  # noqa: E402


def load_digits_data():
    try:
        from sklearn.datasets import load_digits

        d = load_digits()
        return d.data.astype(np.float32), d.target
    except Exception:
        from sq_learn_tpu.datasets import load_digits as _ld

        return _ld()


def main():
    probe_backend()
    X, y = load_digits_data()
    k, n_init, max_iter, seed = 10, 10, 300, 0

    from sq_learn_tpu.models import QKMeans

    est = QKMeans(n_clusters=k, n_init=n_init, max_iter=max_iter,
                  delta=0.5, true_distance_estimate=False,  # delta-means mode
                  random_state=seed)
    est.fit(X)  # warm-up: compile + first run
    # fit materializes NumPy outputs (labels_, cluster_centers_), so
    # wall-clock needs no extra device sync; min-of-3 suppresses host noise
    ours = min(_timed(est.fit, X) for _ in range(3))

    sk_time = None
    ari = None
    inertia_ratio = None
    try:
        from sklearn.cluster import KMeans as SKKMeans
        from sklearn.metrics import adjusted_rand_score

        sk = SKKMeans(n_clusters=k, n_init=n_init, max_iter=max_iter,
                      random_state=seed)
        sk.fit(X)  # warm-up caches
        sk_time = min(_timed(sk.fit, X) for _ in range(3))
        inertia_ratio = float(est.inertia_ / sk.inertia_)
        # ARI between two independently-seeded k-means runs is local-optimum
        # noise (sklearn seed-to-seed spans ~0.96-0.98 on digits); report
        # the median over 3 of our seeds against the fixed sklearn fit
        aris = [float(adjusted_rand_score(sk.labels_, est.labels_))]
        for s in (1, 2):  # seed 0 is the timed fit above — reuse its labels
            q = QKMeans(n_clusters=k, n_init=n_init, max_iter=max_iter,
                        delta=0.5, true_distance_estimate=False,
                        random_state=s).fit(X)
            aris.append(float(adjusted_rand_score(sk.labels_, q.labels_)))
        ari = sorted(aris)[1]
    except Exception as exc:  # sklearn missing: report absolute time only
        print(f"# sklearn baseline unavailable: {exc}", file=sys.stderr)

    import jax

    result = {
        "metric": "qkmeans_digits_1797x64_k10_fit_wallclock",
        "value": round(ours, 4),
        "unit": "s",
        # null = no baseline measured; run_suite.sh's gate counts it a miss
        "vs_baseline": round(sk_time / ours, 3) if sk_time else None,
        "backend": jax.default_backend(),
        # where the fit actually ran: on an accelerator backend the
        # size-aware dispatch routes digit-scale fits to the host engines
        # ('cpu:tiny-routed') so the headline no longer hinges on tunnel
        # health — this field keeps the record honest about that choice
        "engine": getattr(est, "fit_backend_", "unknown"),
    }
    if ari is not None:
        result["ari_vs_sklearn_median3"] = round(ari, 3)
        result["inertia_vs_sklearn"] = round(inertia_ratio, 5)
        print(f"# sklearn={sk_time:.4f}s ARI(median over 3 seeds)={ari:.3f} "
              f"inertia ratio={inertia_ratio:.5f}", file=sys.stderr)
    # SQ_OBS=1: the headline line gains compile/transfer/probe totals so
    # BENCH_*.json tracks observability regressions alongside latency.
    # The MFU gauge is priced first so the snapshot's measured_mfu field
    # carries this fit's number: FLOPs = the Lloyd E+M GEMMs at this
    # shape × the iterations the timed fit actually ran × restarts
    # (utils/profiling.lloyd_iter_flops — the same roofline accounting
    # bench_pallas_mfu uses), over the measured wall-clock.
    try:
        from sq_learn_tpu import obs as _sqobs

        if _sqobs.enabled():
            from sq_learn_tpu.utils import profiling

            n_iter = max(1, int(getattr(est, "n_iter_", 1)))
            fit_flops = (profiling.lloyd_iter_flops(*X.shape, k)
                         * n_iter * n_init)
            profiling.mfu(fit_flops, ours)
    except Exception:
        pass  # the headline line must print even if pricing fails
    from bench._common import obs_snapshot

    snap = obs_snapshot()
    if snap is not None:
        result["obs"] = snap
    print(json.dumps(result))


def _timed(fn, *args):
    t0 = time.perf_counter()
    fn(*args)
    return time.perf_counter() - t0


if __name__ == "__main__":
    main()
