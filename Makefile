# Development targets (reference: Makefile:22-27 `make inplace` + `test-code`;
# there is no native build step here — the C++ helper builds itself on first
# import via sq_learn_tpu/native).

PYTHON ?= python
# test-timed uses the `time` shell keyword, which dash (/bin/sh on
# Debian/Ubuntu CI runners) does not have
SHELL := /bin/bash

.PHONY: test test-fast test-timed test-fast-tier test-slow-tier lint \
    lint-selftest bench \
    bench-smoke bench-suite multichip examples \
    hunt obs-smoke faults-smoke oocore-smoke serve-smoke control-smoke \
    elastic-smoke regress-selftest \
    smoke obs-report obs-trace obs-frontier obs-audit obs-budget \
    obs-control obs-fleet obs-storage regress all

all: lint test

# Full suite on the XLA CPU backend with 8 virtual devices (the conftest
# forces this, so sharding paths run without hardware). CI gate.
# SQ_TEST_CLEAR_CACHES=1 clears XLA compile caches between test modules —
# mitigation for the round-5 full-suite segfault at [95%] (compile-cache
# accumulation, VERDICT.md) until root-caused; dev loops (test-fast) keep
# warm caches.
test:
	SQ_TEST_CLEAR_CACHES=1 $(PYTHON) -m pytest tests/ -q

# CI variant: the two tiers run (and are timed) in SEPARATE PROCESSES —
# and in CI as separate steps — so one native XLA crash (the round-5
# [95%] SIGSEGV class) can zero at most one tier's evidence, never the
# round's. PYTHONFAULTHANDLER=1 arms the stdlib crash handler so a
# native-signal death leaves the Python tracebacks of every thread in
# the tier's log; each tier's full output is captured under test-logs/
# (CI uploads the directory as an artifact — VERDICT r5 #1
# follow-through beyond the SQ_TEST_CLEAR_CACHES mitigation). Budget:
# fast ≤5 min / full ≤15 min on a quiet host; a drifting tier shows up
# in the log instead of silently eating the iteration loop.
test-fast-tier:
	@mkdir -p test-logs
	@echo "== fast tier (-m 'not slow') =="
	set -o pipefail; time env SQ_TEST_CLEAR_CACHES=1 PYTHONFAULTHANDLER=1 \
	    $(PYTHON) -m pytest tests/ -q -m "not slow" 2>&1 \
	    | tee test-logs/fast-tier.log

test-slow-tier:
	@mkdir -p test-logs
	@echo "== slow tier (-m slow) =="
	set -o pipefail; time env SQ_TEST_CLEAR_CACHES=1 PYTHONFAULTHANDLER=1 \
	    $(PYTHON) -m pytest tests/ -q -m "slow" 2>&1 \
	    | tee test-logs/slow-tier.log

test-timed: test-fast-tier test-slow-tier

# Quick signal: everything except the heavyweight tier (statistical
# distribution tests, multi-process mesh, driver gates — ~40% of suite
# wall-clock in ~5% of the tests). CI runs the full suite.
test-fast:
	$(PYTHON) -m pytest tests/ -q -m "not slow"

# Bytecode-compile every tree, then sqcheck: the project-native invariant
# rules (docs/static_analysis.md) + the generated-docs drift gate. flake8
# still runs in CI where installable; sqcheck is stdlib-only and runs
# everywhere.
lint:
	$(PYTHON) -m compileall -q sq_learn_tpu tests bench examples \
	    bench.py __graft_entry__.py
	$(PYTHON) -m sq_learn_tpu.analysis --check-docs

# Prove every sqcheck rule still fires on its broken fixture (and stays
# quiet on the good twin) — a rule that silently stopped matching is
# worse than no rule.
lint-selftest:
	$(PYTHON) -m sq_learn_tpu.analysis --selftest

# Headline benchmark (BASELINE.md config #1) — one JSON line.
bench:
	$(PYTHON) bench.py

# All five BASELINE configs in smoke mode (tiny shapes, CPU-safe).
bench-smoke:
	SQ_BENCH_SMOKE=1 $(PYTHON) -m bench.bench_qpca_mnist
	SQ_BENCH_SMOKE=1 $(PYTHON) -m bench.bench_qkmeans_mnist
	SQ_BENCH_SMOKE=1 $(PYTHON) -m bench.bench_randomized_svd_covtype
	SQ_BENCH_SMOKE=1 $(PYTHON) -m bench.bench_qkmeans_cicids_sweep
	SQ_BENCH_SMOKE=1 $(PYTHON) -m bench.bench_estimator_surfaces
	SQ_BENCH_SMOKE=1 $(PYTHON) -m bench.bench_pallas_mfu
	SQ_BENCH_SMOKE=1 $(PYTHON) -m bench.bench_ipe_digits
	SQ_BENCH_SMOKE=1 $(PYTHON) -m bench.bench_qpca_error_sweep
	JAX_PLATFORMS=cpu $(PYTHON) -m bench.tpu_kernel_smoke

# The example drivers (streaming_fit stays manual: its accelerator probe
# waits out a wedged tunnel for ~2 min before falling back; the rest
# finish in about a minute total on CPU — mnist_trial's exact-tomography
# qPCA fit runs in seconds since the host tomography twin).
examples:
	$(PYTHON) examples/qpca_demo.py
	$(PYTHON) examples/tomography_histogram.py
	$(PYTHON) examples/sharded_fit.py
	$(PYTHON) examples/mnist_trial.py
	$(PYTHON) examples/delta_tradeoff.py
	$(PYTHON) examples/qpca_error_tradeoff.py --subsample 4000 --folds 3
	$(PYTHON) examples/runtime_tradeoff.py

# The driver's multichip gate, runnable locally.
multichip:
	$(PYTHON) -c "import __graft_entry__ as g; g.dryrun_multichip(8); \
	    print('dryrun_multichip(8) ok')"

# Observability smoke: a tiny streamed fit + quantum extraction under
# SQ_OBS=1, then schema validation of the emitted JSONL (the CI-runnable
# contract check for the obs layer; pins the CPU backend in-process, so a
# wedged tunnel cannot hang it).
obs-smoke:
	env SQ_OBS=1 SQ_OBS_PATH=/tmp/sq_obs_smoke.jsonl \
	    $(PYTHON) -m sq_learn_tpu.obs.smoke

# Resilience smoke: a streamed fit under an injected fault schedule
# (transient transfer failure, probe timeout, mid-pass interrupt+resume,
# breaker trip) on the CPU backend; asserts fault-free/faulted/resumed
# parity and validates the emitted fault/breaker JSONL against the
# schema. The CI-runnable contract check for sq_learn_tpu.resilience.
faults-smoke:
	env SQ_OBS=1 SQ_OBS_PATH=/tmp/sq_faults_smoke.jsonl \
	    $(PYTHON) -m sq_learn_tpu.resilience.smoke

# Regression-gate self-test: a REAL forced-retracing injection (shape
# leaked into a tracked jit) must produce a red compile_count verdict
# against a clean baseline run, and an unmodified rerun must stay green.
regress-selftest:
	$(PYTHON) -m sq_learn_tpu.obs regress --selftest

# Out-of-core smoke: tiny shard store + its lz4-compressed twin ->
# fault-injected multi-epoch fit over the COMPRESSED store WITH the
# shard readahead prefetcher enabled (read_fail + corrupt_shard fire on
# worker threads, the stored-payload corruption is caught by the
# compressed-bytes CRC before decode, absorbed with bit parity vs the
# uncompressed serial depth-0 reference) -> REAL subprocess SIGKILL
# mid-epoch mid-prefetch on the compressed store -> resume from the
# mid-epoch checkpoint -> bit-parity assert vs the uninterrupted fit,
# plus schema validation of the read-fault JSONL and the
# prefetch/codec counters. The CI-runnable contract check for
# sq_learn_tpu.oocore.
oocore-smoke:
	env SQ_OBS=1 SQ_OBS_PATH=/tmp/sq_oocore_smoke.jsonl \
	    $(PYTHON) -m sq_learn_tpu.oocore.smoke

# Serving smoke: checkpointed tenants (plus bf16/int8 quantized
# registrations) behind the micro-batching dispatcher — AOT warm FIRST
# (whole bucket ladder, persistent compile cache armed at a fresh dir),
# then watchdog budgets pinned to 0 under SQ_OBS_STRICT=1: a single
# serving-path jit compile fails the smoke. Digest-verified registry
# loads, mixed-size/type/tenant load with estimator parity, result-cache
# hit, one absorbed transfer fault with bit parity, quantized responses
# within the declared (ε, δ) fold on EVERY request under
# SQ_OBS_AUDIT_STRICT=1, a feature-cache spill leg (RAM eviction ->
# compressed disk entry -> digest-verified disk hit -> FRESH process
# replays the same bytes off disk with zero jit compiles), >=1
# persistent-cache hit in a second process,
# and schema validation of the emitted JSONL incl. >=1 `slo` +
# `guarantee` record. The CI-runnable contract check for
# sq_learn_tpu.serving.
serve-smoke:
	env SQ_OBS=1 SQ_OBS_PATH=/tmp/sq_serve_smoke.jsonl \
	    $(PYTHON) -m sq_learn_tpu.serving.smoke

# Control-plane smoke: the SLO-driven (ε, δ) autotuner + admission
# control contract end to end — register-time frontier plan (int8 for
# the ε-headroom tenant), forced burn under SQ_OBS_BUDGET_STRICT=1
# (the controller must renegotiate BEFORE the multi-window alert can
# trip: zero alert records, no raise), cheapest-first ladder order
# (widen before host) with zero lost requests and estimator-parity
# responses through the host rung, a relaxed δ-headroom tenant banking
# theoretical runtime, and schema-v8 validation of the ≥1 `control`
# records plus the stdlib read side rendering the predicted/realized
# loop. The CI-runnable contract check for sq_learn_tpu.serving.control.
control-smoke:
	env SQ_OBS=1 SQ_OBS_PATH=/tmp/sq_control_smoke.jsonl \
	    $(PYTHON) -m sq_learn_tpu.serving.control_smoke

# Elastic-mesh smoke: topology-invariant fold parity at 1/2/3 logical
# hosts, then a REAL 2-worker multi-process fit (gloo collectives,
# coordinator-hosted KV service) bit-equal to the simulator, then a
# REAL 3-worker fit with one worker SIGKILLed mid-epoch — lease-layer
# detection, generation-bumping shrink to 2 hosts, resume from the
# committed checkpoint, final state bit-identical to the uninterrupted
# run with every shard folded exactly `epochs` times (zero lost, zero
# double-folded), plus schema-v10 validation of every worker's elastic
# transition records AND of the run's merged fleet timeline: one
# coordinator-minted run_id across every per-process shard, monotone
# clock-aligned merge, the SIGKILLed worker's fold progress up to its
# last pre-kill flush, commit-ledger reconciliation (every committed
# window exactly once) and a generation-1 detect→shrink→resume
# critical path — the merged artifact is archived outside the scratch
# dir. The CI-runnable contract check for sq_learn_tpu.parallel.elastic
# + sq_learn_tpu.obs.fleet.
elastic-smoke:
	$(PYTHON) -m sq_learn_tpu.parallel.elastic_smoke

# All contract smokes (observability + resilience + out-of-core +
# serving + control plane + elastic mesh + regression gate).
# obs-storage rides right after oocore-smoke: it renders that smoke's
# artifact and exits 2 if the faulted compressed fit left zero io
# records — the storage-plane ledger's CI presence check.
smoke: obs-smoke faults-smoke oocore-smoke obs-storage serve-smoke \
    control-smoke elastic-smoke regress-selftest lint-selftest

# Render the human report / Chrome trace of an obs JSONL artifact
# (default: the obs-smoke artifact; override with OBS=<path>).
OBS ?= /tmp/sq_obs_smoke.jsonl
obs-report:
	$(PYTHON) -m sq_learn_tpu.obs report $(OBS)

obs-trace:
	$(PYTHON) -m sq_learn_tpu.obs trace $(OBS) -o $(OBS).trace.json

# Statistical-observability views of the same artifact: the (ε, δ)
# guarantee audit (exit 1 on any flagged site) and the
# accuracy-vs-theoretical-runtime frontier table.
obs-audit:
	$(PYTHON) -m sq_learn_tpu.obs audit $(OBS)

obs-frontier:
	$(PYTHON) -m sq_learn_tpu.obs frontier $(OBS)

# Per-tenant error-budget view of the same artifact: rolling-window
# latency-SLO + statistical burn rates per tenant (exit 1 when any
# multi-window burn alert fired — the CI-friendly burn check).
obs-budget:
	$(PYTHON) -m sq_learn_tpu.obs budget $(OBS)

# Controller-decision view of the same artifact: per-tenant autotuner /
# admission-control history with the predicted-vs-realized loop (exit 2
# when the artifact carries zero control records — "no telemetry" must
# never read as "nothing to decide").
obs-control:
	$(PYTHON) -m sq_learn_tpu.obs control $(OBS)

# Fleet view: merge one elastic run's per-process obs shards (a run
# directory of obs.*.jsonl files, or explicit shard paths via
# FLEET=<src>) into one clock-aligned timeline — per-host/per-generation
# rollups, the detect→shrink→re-init→resume critical path per shrink,
# and the commit-ledger reconciliation (exit 1 when a committed window
# is missing or duplicated, exit 2 when the source holds no shards).
FLEET ?= /tmp/sq_obs_smoke.jsonl
obs-fleet:
	$(PYTHON) -m sq_learn_tpu.obs fleet $(FLEET)

# Storage-plane view: per-surface accounting (oocore shards / serving
# feature cache / persistent compile cache) + the per-shard heat×bytes
# table from the artifact's io records, with the tiering advisor's
# compress/decompress/leave recommendations projected from the run's
# own measured codec ratio and latencies (exit 2 when the artifact
# carries zero io records — "no telemetry" must never read as "healthy
# storage"). Default artifact: the oocore smoke's, whose faulted
# compressed prefetched fit feeds every ledger path.
STORAGE ?= /tmp/sq_oocore_smoke.jsonl
obs-storage:
	$(PYTHON) -m sq_learn_tpu.obs storage $(STORAGE) --advise

# Perf-regression gate, standalone: run the headline bench, the PR 6
# fused-fit bench (classical 70k×784 q-means), the PR 7 δ=0.5
# 70k×784 headline (sketched spectral stats — the line whose band pins
# the sketch engine's win), AND the PR 8 out-of-core fit (100k×784 shard
# store over a 96 MB RAM budget, with the killed-and-resumed leg), AND
# the PR 9/11 serving load bench (12k mixed requests through the
# AOT-warmed micro-batching dispatcher: QPS lower-bounded by the
# `throughput` gate, p99 upper-bounded by the latency gate, cold-start
# p99 ratio floored at 5.0 and the bf16 bytes ratio floored at 1.8 by
# the history-free vs_baseline gate) under
# SQ_OBS=1 and band every line (latency,
# compile_count, total_transfer_bytes, peak HBM) against the committed
# BENCH_r*.json trajectory + bench/records history. Exit 1 on any red
# verdict. CI runs this after the timed tiers (widened latency tolerance
# for runner-class variance; the compile/transfer gates stay tight).
regress:
	env SQ_OBS=1 SQ_OBS_PATH=/tmp/sq_regress_obs.jsonl \
	    $(PYTHON) bench.py > /tmp/sq_regress_bench.json
	env SQ_OBS=1 SQ_OBS_PATH=/tmp/sq_regress_fused_obs.jsonl \
	    $(PYTHON) -m bench.bench_qkmeans_fused_fit \
	    >> /tmp/sq_regress_bench.json
	env SQ_OBS=1 SQ_OBS_PATH=/tmp/sq_regress_mnist_obs.jsonl \
	    $(PYTHON) -m bench.bench_qkmeans_mnist \
	    >> /tmp/sq_regress_bench.json
	env SQ_OBS=1 SQ_OBS_PATH=/tmp/sq_regress_oocore_obs.jsonl \
	    $(PYTHON) -m bench.bench_oocore_fit \
	    >> /tmp/sq_regress_bench.json
	env SQ_OBS=1 SQ_OBS_PATH=/tmp/sq_regress_serving_obs.jsonl \
	    $(PYTHON) -m bench.bench_serving_load \
	    >> /tmp/sq_regress_bench.json
	env SQ_OBS=1 SQ_OBS_PATH=/tmp/sq_regress_elastic_obs.jsonl \
	    $(PYTHON) -m bench.bench_elastic_fit \
	    >> /tmp/sq_regress_bench.json
	cat /tmp/sq_regress_bench.json
	$(PYTHON) -m sq_learn_tpu.obs regress /tmp/sq_regress_bench.json --root .

# Full BASELINE suite (headline + configs #2-#5) into one record file.
bench-suite:
	bash bench/run_suite.sh

# Round-long automated TPU window hunt: probe every ~4 min, fire the
# window runbook on the first healthy probe, log every attempt.
hunt:
	bash bench/hunt_tpu_window.sh
