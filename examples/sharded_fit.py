"""Multi-device (and multi-host) fitting with a jax.sharding.Mesh.

The reference parallelizes with OpenMP threads and a multiprocessing pool
(SURVEY §2.3); the TPU-native equivalent is SPMD over a device mesh. Both
flagship estimators take a ``mesh``:

- ``QKMeans(mesh=...)`` runs the Lloyd loop under ``shard_map`` with psum
  centroid/inertia reductions over ICI.
- ``QPCA(mesh=...)`` computes the fit SVD from a sample-sharded Gram
  contraction (per-shard GEMMs + one m×m all-reduce), and its quantum
  transform draws tomography estimates in-shard.
- ``KNeighborsClassifier(mesh=...)`` shards the TRAINING corpus: each
  device searches its shard, only (n_q, k) candidate lists cross ICI.
- ``TruncatedSVD(mesh=...)`` is the uncentered variant of the qPCA path.

On a pod slice this script runs unchanged over the real chips; here it
demonstrates on however many devices the backend exposes (the test suite
forces 8 virtual CPU devices; under an axon tunnel it is the one TPU). For
multi-HOST pods, call ``sq_learn_tpu.parallel.distributed.initialize()``
first and build the mesh from ``global_mesh()`` — see
``tests/_dist_worker.py`` for a complete two-process program.

Run: python examples/sharded_fit.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import ensure_backend  # noqa: E402

ensure_backend()


import warnings

import numpy as np
import jax

from sq_learn_tpu.datasets import load_digits, make_blobs
from sq_learn_tpu.models import QKMeans, QPCA
from sq_learn_tpu.parallel import make_mesh

warnings.filterwarnings("ignore")


def main():
    devices = jax.devices()
    mesh = make_mesh(devices)
    print(f"mesh: {len(devices)} x {devices[0].platform}")

    # data-parallel q-means (delta-means noise mode)
    X, y = make_blobs(n_samples=4003, centers=5, n_features=16,
                      random_state=0)  # 4003: uneven shards exercise padding
    km = QKMeans(n_clusters=5, delta=0.5, true_distance_estimate=False,
                 n_init=2, random_state=0, mesh=mesh).fit(X)
    print(f"q-means: inertia={km.inertia_:.1f} n_iter={km.n_iter_} "
          f"clusters={len(np.unique(km.labels_))}")

    # data-parallel qPCA (classical fit; quantum estimators compose the
    # same way — they consume the spectrum, which is replicated)
    Xd, yd = load_digits()
    pca = QPCA(n_components=16, svd_solver="full", mesh=mesh,
               random_state=0).fit(Xd)
    print(f"qPCA: explained variance ratio (top-16) = "
          f"{pca.explained_variance_ratio_.sum():.4f}")

    # ...and its tomography-noised transform, drawn in-shard over the mesh
    noisy = pca.transform(Xd[:64], classic_transform=False,
                          quantum_representation=True, epsilon_delta=0.5,
                          norm="None", psi=0.5)
    Zq = np.asarray(noisy["quantum_representation_results"])
    print(f"qPCA quantum transform (sharded tomography): shape={Zq.shape}")

    # train-sharded KNN: the corpus lives on its shards, every search
    # merges per-shard candidate lists over ICI
    from sq_learn_tpu.models import KNeighborsClassifier, TruncatedSVD

    knn = KNeighborsClassifier(n_neighbors=5, mesh=mesh).fit(Xd, yd)
    acc = float((knn.predict(Xd[:300]) == yd[:300]).mean())
    print(f"sharded KNN: train accuracy on 300 digits = {acc:.3f}")

    # uncentered sharded SVD (the LSA/TruncatedSVD contract)
    tsvd = TruncatedSVD(n_components=8, mesh=mesh).fit(Xd)
    print(f"sharded TruncatedSVD: top singular value = "
          f"{tsvd.singular_values_[0]:.1f}")


if __name__ == "__main__":
    main()
