"""Tomography-error histogram experiment.

The working equivalent of the reference's ``sklearn/Sheet1.py`` (which calls
a nonexistent ``make_noisy_vec`` — SURVEY §2.1 "dead"): estimate a random
784-dim unit vector by vector-state tomography at a given δ, across many
seeds at once (one vmapped kernel instead of the reference's host loop),
and histogram the resulting L2 errors against the δ guarantee.

Run: python examples/tomography_histogram.py [--dim 784] [--delta 0.1]
     [--trials 64] [--save hist.png]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import ensure_backend  # noqa: E402

ensure_backend()


import argparse
import time

import numpy as np
import jax
import jax.numpy as jnp

from sq_learn_tpu.ops.quantum import real_tomography
from sq_learn_tpu.ops.quantum.tomography import tomography_n_measurements


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=784)
    ap.add_argument("--delta", type=float, default=0.1)
    ap.add_argument("--trials", type=int, default=64)
    ap.add_argument("--norm", choices=["L2", "inf"], default="L2")
    ap.add_argument("--save", default=None, help="write a histogram PNG")
    args = ap.parse_args()

    key = jax.random.PRNGKey(0)
    key, kv = jax.random.split(key)
    v = jax.random.normal(kv, (args.dim,))
    v = v / jnp.linalg.norm(v)

    N = tomography_n_measurements(args.dim, args.delta, norm=args.norm)
    print(f"dim={args.dim} delta={args.delta} -> N={N} measurements/trial")

    t0 = time.perf_counter()
    keys = jax.random.split(key, args.trials)
    estimates = jax.vmap(
        lambda k: real_tomography(k, v, delta=args.delta, norm=args.norm)
    )(keys)
    diff = estimates - v[None, :]
    # measure the error in the norm whose guarantee N was sized for
    if args.norm == "L2":
        errors = np.asarray(jnp.linalg.norm(diff, axis=1))
    else:
        errors = np.asarray(jnp.max(jnp.abs(diff), axis=1))
    wall = time.perf_counter() - t0

    within = float((errors <= args.delta).mean())
    print(f"{args.trials} trials in {wall:.2f}s: "
          f"mean {args.norm} err {errors.mean():.4f}, "
          f"max {errors.max():.4f}, P(err <= delta) = {within:.2%}")

    if args.save:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        plt.hist(errors, bins=30)
        plt.axvline(args.delta, color="red", linestyle="--",
                    label=f"delta={args.delta}")
        plt.xlabel(f"{args.norm} tomography error")
        plt.ylabel("trials")
        plt.legend()
        plt.savefig(args.save, dpi=120)
        print(f"histogram -> {args.save}")


if __name__ == "__main__":
    main()
