"""Shared example plumbing: accelerator probe with CPU fallback.

The default environment points JAX at a tunneled accelerator whose relay
can wedge backend init indefinitely (see CLAUDE.md gotchas). Every example
calls :func:`ensure_backend` before its first jax operation: the configured
platform is probed in a throwaway subprocess with a timeout, and on
failure the process is pinned to the CPU backend via the documented
in-process override. Same contract as ``bench/_common.probe_backend``.
"""

import os
import subprocess
import sys


def ensure_backend(timeout_s=90):
    platform = os.environ.get("JAX_PLATFORMS", "")
    if platform in ("", "cpu"):
        if platform == "cpu":
            import jax

            jax.config.update("jax_platforms", "cpu")
        return
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, check=True, capture_output=True)
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
        print(f"# backend {platform!r} unreachable; falling back to CPU",
              file=sys.stderr)
        import jax

        jax.config.update("jax_platforms", "cpu")
