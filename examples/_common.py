"""Shared example plumbing: accelerator probe with CPU fallback.

One definition for the wedged-tunnel escape (see CLAUDE.md gotchas) —
re-exported from the bench suite's probe so the two cannot drift.
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench._common import probe_backend as ensure_backend  # noqa: E402,F401
