"""The framework's thesis as one runnable driver: δ is an explicit
accuracy/runtime dial.

Sweeps the q-means quantum error budget δ over an overlapping-class
dataset (the CICIDS-shaped surrogate, whose graded near-duplicate class
pairs merge progressively as δ grows — reference ``README.rst:26-44``
describes exactly this trade-off without ever measuring it) and prints
ARI + wall-clock per δ beside a classical sklearn KMeans baseline.

Run: python examples/delta_tradeoff.py [--n-samples 20000] [--n-init 10]

Deliberately NOT the same configuration as the BASELINE bench
(``bench/bench_qkmeans_cicids_sweep.py``: 50k rows, n_init=3 — pinned by
BASELINE.md): this driver optimizes for a clean demonstration at a
smaller default size, where 3 restarts can land in a pair-merging local
optimum that muddies the curve; n_init=10 (sklearn's own default) makes
δ the only variable.
"""

import argparse
import os
import sys
import time
import warnings

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import ensure_backend  # noqa: E402

ensure_backend()
warnings.filterwarnings("ignore")

import numpy as np  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-samples", type=int, default=20_000)
    # sklearn's KMeans default; fewer restarts can land in a
    # pair-merging local optimum (see module docstring)
    ap.add_argument("--n-init", type=int, default=10)
    args = ap.parse_args()

    from sq_learn_tpu.datasets import load_cicids
    from sq_learn_tpu.metrics import adjusted_rand_score
    from sq_learn_tpu.models import QKMeans
    from sq_learn_tpu.preprocessing import StandardScaler

    X, y, real = load_cicids(n_samples=args.n_samples)
    if len(X) > args.n_samples:
        # the real-CSV branch of load_cicids returns every row; honor the
        # flag by subsampling (deterministic) so quick demos stay quick
        idx = np.random.default_rng(0).choice(
            len(X), args.n_samples, replace=False)
        X, y = X[idx], y[idx]
    X = StandardScaler().fit_transform(X)
    k = int(len(np.unique(y)))
    print(f"dataset: {X.shape[0]}x{X.shape[1]}, k={k} "
          f"({'real CICIDS' if real else 'surrogate'})")

    try:
        from sklearn.cluster import KMeans as SKKMeans
        from sklearn.metrics import adjusted_rand_score as sk_ari

        t0 = time.perf_counter()
        sk = SKKMeans(n_clusters=k, n_init=args.n_init, random_state=0).fit(X)
        print(f"classical sklearn KMeans: ARI "
              f"{sk_ari(y, sk.labels_):.3f} in "
              f"{time.perf_counter() - t0:.2f}s  (the exact answer at "
              f"full classical cost)")
    except Exception as exc:
        print(f"(classical sklearn baseline unavailable: {exc} — "
              "showing the δ-sweep alone)")

    print(f"{'δ':>5} | {'ARI':>6} | {'fit s':>7} | note")
    for delta in (0.0, 0.1, 0.3, 0.5, 1.0):
        est = QKMeans(n_clusters=k, n_init=args.n_init, delta=delta,
                      true_distance_estimate=False, random_state=0)
        t0 = time.perf_counter()
        est.fit(X)
        t = time.perf_counter() - t0
        ari = float(adjusted_rand_score(y, est.labels_))
        note = ("exact classical Lloyd" if delta == 0
                else "δ-window label noise")
        print(f"{delta:5.1f} | {ari:6.3f} | {t:7.3f} | {note}")
    print("\nδ=0 matches classical quality; growing δ trades clustering "
          "accuracy for a cheaper quantum circuit — the dial the "
          "reference's README describes.")


if __name__ == "__main__":
    main()
