"""Accuracy vs theoretical quantum runtime — the paper's trade-off, measured.

The framework's thesis (reference ``README.rst:26-44``) is that ε/δ are
*runtime* parameters: a looser error budget buys theoretical quantum
runtime and costs accuracy. This driver states that trade-off end to end
with the framework's own instruments:

1. a q-means δ-sweep on clustered synthetic data — measured ARI per δ
   joined with ``QKMeans.quantum_runtime_model`` (the closed-form q-means
   cost, reference ``_dmeans.py:1440-1449``);
2. a qPCA ε+δ-sweep — downstream 1-NN accuracy on the tomography-noised
   projection joined with ``QPCA.accumulate_q_runtime`` (the QADRA
   accountant, reference ``_qPCA.py:1123-1208``);

every point lands as a schema-valid ``tradeoff`` JSONL record, the
guarantee auditor checks the simulated routines honored their declared
(ε, δ) along the way, and the script ends by rendering the frontier
table (``python -m sq_learn_tpu.obs frontier`` over the same artifact
reproduces it).

Usage: python examples/runtime_tradeoff.py [--out /tmp/tradeoff.jsonl]
"""

import sys

import numpy as np

from _common import ensure_backend


def main():
    ensure_backend()
    out_path = "/tmp/sq_runtime_tradeoff.jsonl"
    if "--out" in sys.argv:
        out_path = sys.argv[sys.argv.index("--out") + 1]

    from sq_learn_tpu import obs
    from sq_learn_tpu.metrics import adjusted_rand_score
    from sq_learn_tpu.models import QPCA, QKMeans

    open(out_path, "w").close()
    obs.enable(out_path)

    rng = np.random.default_rng(0)
    k, m = 6, 32
    # tight margins (center scale ~ cluster scale) so the error dials
    # visibly bend the accuracy instead of saturating at 1.0
    centers = rng.normal(scale=1.6, size=(k, m))
    X = np.concatenate([
        rng.normal(loc=c, scale=1.0, size=(512, m)) for c in centers
    ]).astype(np.float32)
    y = np.repeat(np.arange(k), 512)
    perm = rng.permutation(len(X))  # class-stratified holdout splits
    X, y = X[perm], y[perm]

    # -- leg 1: q-means δ-sweep (ARI vs quantum_runtime_model) ----------
    print("q-means δ-sweep:")
    for delta in (0.0, 2.0, 8.0, 32.0):
        est = QKMeans(n_clusters=k, n_init=2, delta=delta,
                      true_distance_estimate=False, random_state=0).fit(X)
        ari = float(adjusted_rand_score(y, est.labels_))
        q_rt = c_rt = None
        if delta > 0:
            quantum, classical = est.quantum_runtime_model(*X.shape)
            q_rt, c_rt = float(np.ravel(quantum)[0]), float(classical)
        obs.frontier.record_tradeoff(
            "example_qkmeans_delta", delta, accuracy=ari,
            accuracy_metric="ari", q_runtime=q_rt, c_runtime=c_rt,
            budget={"delta": delta})
        print(f"  delta={delta:<4}  ari={ari:.4f}  "
              f"q_runtime={'-' if q_rt is None else f'{q_rt:.3e}'}")

    # -- leg 2: qPCA ε+δ-sweep (1-NN acc vs accumulate_q_runtime) ------
    from sq_learn_tpu.models import KNeighborsClassifier

    n_comp = 8
    pca = QPCA(n_components=n_comp, svd_solver="full", random_state=0)
    pca.fit(X)
    # the QADRA twin fits a subsample, so θ must come from the SAME
    # subsample's spectrum (σ scales with √n — a full-data median would
    # select nothing on the twin and zero out the cost model)
    sub = X[:1024]
    theta = float(np.median(
        QPCA(n_components=n_comp, svd_solver="full",
             random_state=0).fit(sub).singular_values_))
    split = len(X) // 2
    knn = KNeighborsClassifier(n_neighbors=1)
    print("qPCA ε+δ-sweep:")
    for err in (0.4, 1.6, 6.4):
        out = pca.transform(
            X, classic_transform=False, epsilon_delta=err,
            quantum_representation=True, norm="est_representation",
            true_tomography=False)
        Xq, _, f_norm = out["quantum_representation_results"]
        acc = float(np.mean(
            knn.fit(Xq[:split], y[:split]).predict(Xq[split:])
            == y[split:]))
        # the QADRA accountant at this point's ε = δ = err/2 (a twin fit
        # carries the flags; the cost is evaluated at the full shape)
        q = QPCA(n_components=n_comp, svd_solver="full", random_state=0)
        q.fit(sub, estimate_all=True, theta_major=theta,
              eps=err / 2, delta=err / 2, true_tomography=False)
        q_rt = float(np.sum([np.asarray(c, float)
                             for c in q.accumulate_q_runtime(*X.shape)]))
        obs.frontier.record_tradeoff(
            "example_qpca_eps_delta", err, accuracy=acc,
            accuracy_metric="holdout_1nn_acc", q_runtime=q_rt,
            c_runtime=float(X.shape[0]) * float(X.shape[1]) ** 2,
            budget={"eps": err / 2, "delta": err / 2},
            f_norm_err=float(f_norm))
        print(f"  eps+delta={err:<4}  acc={acc:.4f}  q_runtime={q_rt:.3e}")

    # -- the artifact: audit + frontier over this run's records ---------
    audit = obs.guarantees.audit()
    flagged = sorted(s for s, a in audit.items() if a["flagged"])
    print("\nguarantee audit "
          f"({sum(a['trials'] for a in audit.values())} draws):")
    print(obs.guarantees.render(audit))
    rec = obs.get_recorder()
    sweeps = obs.frontier.collect(rec.tradeoff_records)
    print("\naccuracy vs theoretical quantum runtime:")
    print(obs.frontier.render(sweeps))
    obs.disable()
    print(f"\nartifact: {out_path} "
          f"(render with: python -m sq_learn_tpu.obs frontier {out_path})")
    if flagged:
        sys.exit(f"guarantee audit flagged: {flagged}")


if __name__ == "__main__":
    main()
