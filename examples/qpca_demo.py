"""qPCA documentation example.

The working equivalent of the reference's ``sklearn/Sheet.py`` (runs the
docstring example fit): fit qPCA on a small matrix with every quantum
estimator enabled, print the estimated spectrum and retained variance, and
compare quantum-vs-classical theoretical runtime surfaces.

Run: python examples/qpca_demo.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import ensure_backend  # noqa: E402

ensure_backend()


import warnings

import numpy as np

from sq_learn_tpu.datasets import load_digits
from sq_learn_tpu.models import QPCA

warnings.filterwarnings("ignore")


def main():
    X, _ = load_digits()

    pca = QPCA(n_components=8, random_state=0)
    pca.fit(X, estimate_all=True, theta_estimate=True, p=0.8,
            eps_theta=0.05, eta=0.05, eps=0.1, delta=0.1,
            true_tomography=False, spectral_norm_est=True,
            condition_number_est=True)

    print("classical singular values:", np.round(pca.singular_values_, 2))
    print("estimated singular values:",
          np.round(pca.estimate_s_values, 2))
    print("spectral norm: true %.2f, estimated %.2f"
          % (pca.spectral_norm, pca.est_spectral_norm))
    print("estimated theta for p=0.8: %.3f" % pca.est_theta)
    print("top-k selected: %d components carrying %.1f%% variance"
          % (pca.topk, 100 * pca.topk_p))

    n_grid, m_grid, q_rt, c_rt = pca.runtime_comparison(100_000, 1_000)
    crossover = q_rt < c_rt
    print("quantum runtime model beats classical on %.1f%% of the "
          "(n<=100k, m<=1k) grid" % (100 * crossover.mean()))


if __name__ == "__main__":
    main()
