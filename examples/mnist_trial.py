"""MNIST qPCA + KNN experiment driver.

The working equivalent of the reference's ``sklearn/MnistTrial.py:10-28``
(which passes a stale ``tomography=True`` kwarg and hits the purely-classical
randomized solver — SURVEY §2.1): fetch MNIST-784, fit qPCA with the quantum
estimators enabled, apply the quantum transform at a chosen total error
ε+δ, and report 10-fold stratified-CV KNN accuracy plus the F-norm deviation
of the estimated representation.

Run: python examples/mnist_trial.py [--n-components 61] [--eps-delta 0.8]
     [--subsample 10000]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import ensure_backend  # noqa: E402

ensure_backend()


import argparse
import time

import numpy as np

from sq_learn_tpu.datasets import load_mnist
from sq_learn_tpu.model_selection import StratifiedKFold, cross_validate
from sq_learn_tpu.models import KNeighborsClassifier, QPCA


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-components", type=int, default=61)
    ap.add_argument("--eps-delta", type=float, default=0.8)
    ap.add_argument("--subsample", type=int, default=10_000,
                    help="rows of MNIST to use (0 = all 70k)")
    ap.add_argument("--folds", type=int, default=10)
    args = ap.parse_args()

    X, y, real = load_mnist()
    if args.subsample:
        X, y = X[: args.subsample], y[: args.subsample]
    print(f"data: {X.shape} ({'real MNIST' if real else 'synthetic surrogate'})")

    eps = delta = args.eps_delta / 2
    t0 = time.perf_counter()
    pca = QPCA(n_components=args.n_components, svd_solver="full",
               random_state=0).fit(
        X, estimate_all=True, eps=eps, delta=delta, theta_major=1e-9,
        true_tomography=False)
    t_fit = time.perf_counter() - t0
    print(f"qPCA fit: {t_fit:.2f}s  (top-k extracted: {pca.topk})")

    t0 = time.perf_counter()
    Xq = pca.transform(X, classic_transform=False,
                       use_classical_components=False)
    t_tr = time.perf_counter() - t0
    Xc = pca.transform(X)
    f_err = np.linalg.norm(Xq - Xc)
    print(f"quantum transform: {t_tr:.2f}s  F-norm deviation vs classic: "
          f"{f_err:.3f}")

    res = cross_validate(
        KNeighborsClassifier(n_neighbors=7), Xq, y,
        cv=StratifiedKFold(args.folds))
    print(f"{args.folds}-fold KNN accuracy: "
          f"{np.mean(res['test_score']):.4f} ± {np.std(res['test_score']):.4f}")


if __name__ == "__main__":
    main()
