"""Streaming clustering on a larger-than-memory CSV, with resume.

Ties three subsystems together:

- ``native.csv_stream_batches`` — the C++ stateful CSV stream (NumPy
  fallback) yields fixed-size batches without loading the file;
- ``MiniBatchQKMeans.partial_fit`` — the incremental-state API (the
  reference's only streaming surface, ``_dmeans.py:2139``, fixed here);
- ``utils.checkpoint`` — the fitted state round-trips to disk mid-stream,
  so an interrupted ingest resumes where it stopped.

Run: python examples/streaming_fit.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import ensure_backend  # noqa: E402

ensure_backend()


import tempfile  # noqa: E402
import warnings  # noqa: E402

import numpy as np  # noqa: E402

from sq_learn_tpu.models import MiniBatchQKMeans  # noqa: E402
from sq_learn_tpu.native import csv_stream_batches, native_available  # noqa: E402
from sq_learn_tpu.utils import load_estimator, save_estimator  # noqa: E402

warnings.filterwarnings("ignore")


def main():
    workdir = tempfile.mkdtemp(prefix="sq_streaming_")
    csv_path = os.path.join(workdir, "events.csv")
    ckpt_dir = os.path.join(workdir, "ckpt")

    # synthesize a "big" file on disk (stand-in for CICIDS-scale logs)
    rng = np.random.default_rng(0)
    centers = rng.normal(scale=10.0, size=(5, 12))
    X = np.vstack([c + rng.normal(size=(4000, 12)) for c in centers])
    rng.shuffle(X)
    np.savetxt(csv_path, X.astype(np.float32), delimiter=",",
               header=",".join(f"f{i}" for i in range(12)))
    print(f"wrote {X.shape[0]} rows to {csv_path} "
          f"(native parser: {native_available()})")

    est = MiniBatchQKMeans(n_clusters=5, delta=0.3,
                           true_distance_estimate=False, random_state=0)
    stream = csv_stream_batches(csv_path, batch_rows=1024)
    for i, batch in enumerate(stream):
        est.partial_fit(batch)
        if i == 9:  # simulate an interruption mid-ingest
            save_estimator(est, ckpt_dir)
            print(f"checkpointed after {est.n_steps_} batches "
                  f"(inertia {est.inertia_:.1f})")
            break

    resumed = load_estimator(ckpt_dir)
    for batch in stream:  # the SAME stream object — ingest continues
        resumed.partial_fit(batch)
    print(f"resumed to {resumed.n_steps_} batches "
          f"(inertia {resumed.inertia_:.1f})")

    labels = resumed.predict(X[:10].astype(np.float32))
    print("labels of first 10 rows:", labels)


if __name__ == "__main__":
    main()
