"""The second estimator's thesis as one runnable driver: ε+δ is an
explicit accuracy/precision dial on qPCA's quantum representation.

Mirrors what ``delta_tradeoff.py`` demonstrates for q-means, on the
reference's own MNIST experiment pattern (``sklearn/MnistTrial.py:10-28``,
``README.rst:26-44``): fit PCA once, then sweep the total tomography error
ε+δ applied to the transformed representation and report, per error level,
the stratified-CV KNN accuracy, the F-norm deviation of the estimated
representation from the exact one, and the transform wall-clock — beside
the classical zero-error baseline.

Three datasets make the demonstration honest offline: the faithful
MNIST-shaped surrogate's synthetic classes have angular margins larger
than any noise the reference's tomography model can produce (its sample
complexity N=36·d·ln d/δ² floors the achievable error), so its accuracy
column stays flat — while the low-margin MNIST-shaped surrogate
(``load_mnist_surrogate_low_margin``, graded class pairs inside the
noise band) and the CICIDS-shaped surrogate's graded near-duplicate
classes both show the dial actually bending.

Run: python examples/qpca_error_tradeoff.py [--subsample 8000] [--folds 5]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import ensure_backend  # noqa: E402

ensure_backend()

import numpy as np  # noqa: E402

ERRORS = (0.2, 0.8, 1.6, 3.2)


def sweep_table(name, pca, X, y, folds):
    from sq_learn_tpu.model_selection import StratifiedKFold, cross_validate
    from sq_learn_tpu.models import KNeighborsClassifier

    def knn_cv(Z):
        res = cross_validate(
            KNeighborsClassifier(n_neighbors=7), Z, y,
            cv=StratifiedKFold(folds))
        return float(np.mean(res["test_score"]))

    acc_c = knn_cv(pca.transform(X))
    print(f"\n{name}: classical transform {folds}-fold KNN accuracy "
          f"{acc_c:.4f}  (the exact answer, ε+δ=0)")
    print(f"{'ε+δ':>5} | {'KNN acc':>8} | {'F-norm err':>10} | "
          f"{'transform s':>11}")
    for err in ERRORS:
        t0 = time.perf_counter()
        out = pca.transform(
            X, classic_transform=False, epsilon_delta=err,
            quantum_representation=True, norm="est_representation",
            true_tomography=True)
        t = time.perf_counter() - t0
        Xq, _, f_norm = out["quantum_representation_results"]
        print(f"{err:5.1f} | {knn_cv(Xq):8.4f} | {f_norm:10.2f} | {t:11.3f}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--subsample", type=int, default=8_000,
                    help="rows of MNIST to use (0 = all 70k)")
    ap.add_argument("--folds", type=int, default=5)
    args = ap.parse_args()

    from sq_learn_tpu.datasets import load_cicids, load_mnist
    from sq_learn_tpu.models import QPCA
    from sq_learn_tpu.preprocessing import StandardScaler

    # the reference's experiment fits classically (svd_solver='full') and
    # applies the quantum error purely at transform time — so one fit
    # serves the whole sweep and ε+δ is the only variable
    X, y, real = load_mnist()
    if args.subsample:
        X, y = X[: args.subsample], y[: args.subsample]
    print(f"MNIST leg: {X.shape} "
          f"({'real MNIST' if real else 'synthetic surrogate'}), "
          f"n_components=61")
    pca = QPCA(n_components=61, svd_solver="full", random_state=0).fit(X)
    sweep_table("MNIST (MnistTrial.py config)", pca, X, y, args.folds)

    from sq_learn_tpu.datasets import load_mnist_surrogate_low_margin

    Xlm, ylm = load_mnist_surrogate_low_margin(args.subsample or 10_000)
    print(f"\nMNIST low-margin leg: {Xlm.shape} (graded-pair surrogate "
          f"with margins inside the tomography noise band), "
          f"n_components=61")
    pca_lm = QPCA(n_components=61, svd_solver="full",
                  random_state=0).fit(Xlm)
    sweep_table("MNIST-shaped (low-margin pairs)", pca_lm, Xlm, ylm,
                args.folds)

    Xc, yc, real_c = load_cicids(n_samples=4_000)
    Xc = StandardScaler().fit_transform(Xc).astype(np.float32)
    print(f"\nCICIDS leg: {Xc.shape} "
          f"({'real CICIDS' if real_c else 'surrogate'}), n_components=10")
    pca_c = QPCA(n_components=10, svd_solver="full", random_state=0).fit(Xc)
    sweep_table("CICIDS (low-margin classes)", pca_c, Xc, yc, args.folds)

    print("\nε+δ=0 is the classical representation; growing the total "
          "tomography error budget degrades the downstream classifier "
          "gracefully while cheapening the quantum circuit — the dial "
          "the reference's MnistTrial sweeps one point of.")


if __name__ == "__main__":
    main()
